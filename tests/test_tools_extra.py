"""SLS simulator, HAR archives, offline image/edits viewers.
Ref: hadoop-sls/SLSRunner.java:105, hadoop-archives + fs/HarFileSystem.java,
tools/offlineImageViewer + offlineEditsViewer."""

import io
import json
import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniDFSCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=2) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


# ------------------------------------------------------------------- SLS


def test_sls_runs_all_schedulers():
    from hadoop_tpu.tools.sls import run
    for kind in ("fifo", "capacity", "fair"):
        r = run(num_nodes=20, num_apps=5, containers_per_app=10,
                scheduler=kind, ticks=500)
        assert r["scheduler"] == kind
        assert r["containers_allocated"] == 50
        assert r["unfinished_apps"] == 0
        assert r["decisions_per_sec"] > 0


def test_sls_capacity_queues_respected():
    from hadoop_tpu.tools.sls import run
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", "a,b")
    conf.set("yarn.scheduler.capacity.root.a.capacity", "50")
    conf.set("yarn.scheduler.capacity.root.b.capacity", "50")
    conf.set("sls.queues", "a,b")
    r = run(num_nodes=10, num_apps=4, containers_per_app=5,
            scheduler="capacity", ticks=300, conf=conf)
    assert r["unfinished_apps"] == 0


# -------------------------------------------------------------- archives


def test_har_roundtrip(fs):
    from hadoop_tpu.tools.archive import HarFileSystem, create_archive
    payload = {}
    fs.mkdirs("/ar/in/sub")
    for name, size in (("/ar/in/a.bin", 50_000),
                       ("/ar/in/sub/b.bin", 120_000),
                       ("/ar/in/sub/c.bin", 7)):
        data = os.urandom(size)
        fs.write_all(name, data)
        payload[name] = data

    index = create_archive(fs, "/ar/in", "/ar/out.har")
    assert index["/"]["dir"] and "/sub/b.bin" in index

    har = HarFileSystem(fs, "/ar/out.har")
    # status + listing
    st = har.get_file_status("/sub/b.bin")
    assert st.length == 120_000 and not st.is_dir
    names = sorted(s.path for s in har.list_status("/sub"))
    assert names == ["/sub/b.bin", "/sub/c.bin"]
    # contents round-trip
    for name, data in payload.items():
        rel = name[len("/ar/in"):]
        assert har.read_all(rel) == data
    # ranged reads via seek
    with har.open("/sub/b.bin") as s:
        s.seek(100_000)
        assert s.read() == payload["/ar/in/sub/b.bin"][100_000:]
    # immutability
    with pytest.raises(PermissionError):
        har.create("/new")
    with pytest.raises(FileNotFoundError):
        har.read_all("/nope")


# --------------------------------------------------------------- oiv/oev


def test_oiv_and_oev_dump(tmp_path):
    from hadoop_tpu.cli.oiv import dump_edits, dump_image
    from hadoop_tpu.dfs.namenode.fsnamesystem import FSNamesystem
    conf = Configuration(load_defaults=False)
    name_dir = str(tmp_path / "name")
    fsn = FSNamesystem(conf, name_dir)
    fsn.load_from_disk()
    fsn.bm.safemode.leave(force=True)
    fsn.mkdirs("/a")
    fsn.mkdirs("/a/b")
    st = fsn.create("/a/f.txt", "client-1", 1, None, False)
    fsn.save_namespace()
    fsn.mkdirs("/after-image")  # lives only in edits
    fsn.close()

    out = io.StringIO()
    n = dump_image(name_dir, out=out)
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    paths = {l.get("path") for l in lines if "path" in l}
    assert {"/", "/a", "/a/b", "/a/f.txt"} <= paths
    types = {l["path"]: l["type"] for l in lines if "path" in l}
    assert types["/a/f.txt"] == "FILE" and types["/a"] == "DIRECTORY"
    assert n >= 4

    out = io.StringIO()
    n = dump_edits(name_dir, out=out)
    ops = [json.loads(l) for l in out.getvalue().splitlines()]
    assert n == len(ops) > 0
    assert any(o["op"] == "mkdir" and o["p"] == "/after-image"
               for o in ops)


# ---------------------------------------------------- timeline / history


def test_timeline_records_app_lifecycle(tmp_path):
    import json as _json
    import urllib.request

    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.yarn.timeline import ApplicationHistoryServer
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        fs2 = cluster.get_filesystem()
        fs2.mkdirs("/tl-in")
        fs2.write_all("/tl-in/x.txt", b"a b a\n")
        job = make_job(cluster.rm_addr, cluster.default_fs, "/tl-in",
                       "/tl-out")
        assert job.wait_for_completion()

        store_dir = cluster.yarn.rm.timeline.store.dir
        conf = Configuration(load_defaults=False)
        ahs = ApplicationHistoryServer(conf, store_dir)
        ahs.init(conf)
        ahs.start()
        try:
            base = (f"http://127.0.0.1:{ahs.port}"
                    "/ws/v1/applicationhistory/apps")
            apps = _json.loads(urllib.request.urlopen(base).read())
            entries = apps["apps"]["app"]
            assert entries, "no apps in timeline"
            app = entries[0]
            assert {"SUBMITTED", "ATTEMPT", "FINISHED"} <= set(app["events"])
            assert app["state"] == "FINISHED"
            one = _json.loads(urllib.request.urlopen(
                f"{base}/{app['id']}").read())
            assert one["app"]["queue"] == "default"
        finally:
            ahs.stop()


# --------------------------------------------------- rumen + dynamometer


def test_rumen_builds_trace_and_sls_replays_it(tmp_path):
    """History done-dir → rumen trace → SLS replay (the reference's
    rumen→gridmix/sls chain)."""
    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.rumen import build_trace
    from hadoop_tpu.tools.sls import SyntheticTrace, run
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        fs2 = cluster.get_filesystem()
        fs2.mkdirs("/ru-in")
        fs2.write_all("/ru-in/x.txt", b"p q r\n" * 20)
        job = make_job(cluster.rm_addr, cluster.default_fs, "/ru-in",
                       "/ru-out")
        assert job.wait_for_completion()
        trace_jobs = build_trace(fs2)
    assert trace_jobs and trace_jobs[0]["containers"] >= 2
    assert trace_jobs[0]["state"] == "SUCCEEDED"
    tr = SyntheticTrace.__new__(SyntheticTrace)
    tr.jobs = trace_jobs
    r = run(num_nodes=5, scheduler="capacity", ticks=200, trace=tr)
    assert r["unfinished_apps"] == 0
    assert r["containers_allocated"] == sum(
        j["containers"] for j in trace_jobs)


def test_dynamometer_replays_audit_log(cluster, fs):
    import logging as _logging

    from hadoop_tpu.tools.dynamometer import parse_audit_line, replay

    # capture a real audit stream from live traffic
    records = []

    class Cap(_logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    audit = _logging.getLogger("hadoop_tpu.audit")
    h = Cap()
    audit.addHandler(h)
    try:
        fs.mkdirs("/dsrc/a")
        fs.write_all("/dsrc/a/f.bin", b"x" * 1000)
        fs.read_all("/dsrc/a/f.bin")
        fs.rename("/dsrc/a/f.bin", "/dsrc/a/g.bin")
    finally:
        audit.removeHandler(h)
    assert records and parse_audit_line(records[0])

    report = replay(fs, records, remap_root="/dynreplay")
    assert report["ops"] >= 4 and report["errors"] == 0
    assert report["per_op"].get("mkdirs", 0) >= 1
    assert fs.exists("/dynreplay/dsrc/a/g.bin")  # the rename replayed


# ---------------------------------------------------------------- gridmix


def test_gridmix_replays_trace_as_real_jobs(tmp_path):
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.gridmix import run_trace
    trace = [
        {"job_id": "job_a", "arrival": 0, "containers": 2},
        {"job_id": "job_b", "arrival": 1, "containers": 1},
    ]
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        report = run_trace(cluster.rm_addr, cluster.default_fs, trace,
                           sleep_ms=50, max_concurrent=2)
        assert report["jobs"] == 2 and report["failed"] == 0
        assert report["job_latency_s"]["p50"] > 0
        fs = cluster.get_filesystem()
        # each job's synthetic maps wrote real committed output
        assert fs.exists("/gridmix-out/0/_SUCCESS")
        parts = [s.path for s in fs.list_status("/gridmix-out/0")
                 if "part-m-" in s.path]
        assert len(parts) == 2


def test_gridmix_submission_policies(tmp_path):
    """The reference's three job-submission policies (ref: hadoop-gridmix
    GridmixJobSubmissionPolicy): SERIAL never overlaps jobs, REPLAY
    holds each job to its trace arrival tick, STRESS floods up to the
    in-flight bound."""
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.gridmix import run_trace

    trace = [{"job_id": f"job_{i}", "arrival": i * 20, "containers": 1}
             for i in range(3)]
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        serial = run_trace(cluster.rm_addr, cluster.default_fs, trace,
                           sleep_ms=50, max_concurrent=3,
                           out_root="/gm-serial", policy="serial")
        assert serial["jobs"] == 3 and serial["failed"] == 0
        assert serial["peak_inflight"] == 1

        # replay: the last job arrives at tick 40 × 0.05 s/tick = 2 s —
        # total wall time can't be shorter than the trace's span
        replay = run_trace(cluster.rm_addr, cluster.default_fs, trace,
                           sleep_ms=50, max_concurrent=3,
                           out_root="/gm-replay", policy="replay",
                           tick_seconds=0.05)
        assert replay["jobs"] == 3 and replay["failed"] == 0
        assert replay["wall_seconds"] >= 40 * 0.05

        with pytest.raises(ValueError):
            run_trace(cluster.rm_addr, cluster.default_fs, trace,
                      policy="bogus")


def test_sls_rm_mode_real_rpc():
    """SLS drives a REAL ResourceManager over its three RPC services
    with simulated NMs + AMs (ref: SLSRunner.java architecture)."""
    from hadoop_tpu.tools.sls import run_rm
    r = run_rm(num_nodes=60, num_apps=3, containers_per_app=8, sweeps=8)
    assert r["mode"] == "rm-rpc"
    assert r["containers_allocated"] == 3 * 8
    assert r["heartbeats"] >= 60 * 8
    assert r["decisions_per_sec"] > 0
    assert r["first_alloc_latency_ms"]["p50"] is not None


def test_dynamometer_generate_and_parallel_replay(tmp_path):
    """Generated audit trace replays multithreaded against a live NN
    (ref: hadoop-dynamometer AuditReplayMapper)."""
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    from hadoop_tpu.tools import dynamometer as dyn
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    trace = str(tmp_path / "audit.log")
    dyn.generate_trace(trace, 1500, workers=4)
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path / "dfs")) as c:
        c.wait_active()
        with open(trace) as f:
            r = dyn.replay_parallel(c.default_fs, list(f), threads=4)
    assert r["ops"] > 1300
    assert r["ops_per_sec"] > 100
    assert set(r["per_op"]) >= {"create", "open", "listStatus"}
    # error rate small (renames/opens racing deletes are tolerated)
    assert r["errors"] < r["ops"] * 0.05


def test_rumen_gridmix_sls_compose_with_load_emulation(tmp_path):
    """The full trace chain (VERDICT r4 #6): run a REAL job, rumen
    extracts a per-phase load model from its counters, gridmix replays
    it as a LoadJob that emulates cpu/record-IO (not sleep), and SLS
    accepts the same trace. The replay's record counters must track
    the model, and its runtime envelope the source job's."""
    import json as _json
    import time as _time

    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.mapreduce import history as jh
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.gridmix import run_trace
    from hadoop_tpu.tools.rumen import build_trace
    from hadoop_tpu.tools.sls import SyntheticTrace, run

    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/lc-in")
        fs.write_all("/lc-in/x.txt", b"alpha beta gamma delta\n" * 500)
        t0 = _time.perf_counter()
        job = make_job(cluster.rm_addr, cluster.default_fs, "/lc-in",
                       "/lc-out")
        assert job.wait_for_completion()
        src_wall = _time.perf_counter() - t0

        trace = build_trace(fs)
        assert trace, "no trace extracted"
        entry = trace[0]
        # the load model is present and shaped by real counters
        assert entry["load"]["map"]["input_records"] == 500 // \
            max(1, entry["load"]["map"]["n"]) * 1  # per-map mean
        assert entry["load"]["map"]["output_records"] > 0
        assert entry["load"]["map"]["output_bytes"] > 0
        assert entry["load"]["reduce"]["input_records"] > 0

        # gridmix LOAD replay (auto-picks load mode)
        t0 = _time.perf_counter()
        report = run_trace(cluster.rm_addr, cluster.default_fs, trace,
                           max_concurrent=1, out_root="/lc-replay")
        replay_wall = _time.perf_counter() - t0
        assert report["jobs"] == 1 and report["failed"] == 0
        # the replayed job produced REAL reduce output (load mode, not
        # sleep: sleep jobs are map-only)
        outs = [s.path for s in fs.list_status("/lc-replay/0")
                if "part-r-" in s.path]
        assert outs, "load replay produced no reduce output"
        # runtime envelope: same order of magnitude as the source job
        # (generous band — 1-core CI host; catches sleep-only or
        # runaway emulation, not percentage drift)
        assert replay_wall < max(6 * src_wall, 60), \
            (src_wall, replay_wall)

        # the replay's own history carries the emulated record flow:
        # map output records within 2x of the model
        replay_trace = build_trace(fs)
        load_jobs = [t for t in replay_trace
                     if t is not entry and t["load"].get("map")]
        assert load_jobs
        got = load_jobs[-1]["load"]["map"]["output_records"]
        want = entry["load"]["map"]["output_records"]
        assert want / 2 <= got <= want * 2, (got, want)

    # SLS accepts the identical trace file (shared format)
    path = str(tmp_path / "trace.json")
    with open(path, "w") as f:
        _json.dump(trace, f)
    tr = SyntheticTrace.from_file(path)
    r = run(num_nodes=4, scheduler="capacity", ticks=200, trace=tr)
    assert r["unfinished_apps"] == 0


@pytest.mark.parametrize("backend", ["jsonl", "sqlite"])
def test_atsv2_reader_flow_run_aggregation(tmp_path, backend):
    """The ATSv2 READER half (VERDICT r4 #8): per-node collectors write
    container entities with resource-time metrics; the reader REST
    aggregates them into apps and flow runs so the timeline answers
    'what did app X / flow Y cost'. Runs once per store backend — the
    sqlite leg is the external-DB-analog path (ref: ATSv2 HBase / v1
    leveldb stores), with the reader auto-detecting the on-disk format."""
    import json as _json
    import urllib.request

    from hadoop_tpu.examples.distributed_shell import submit
    from hadoop_tpu.testing.minicluster import MiniYARNCluster
    from hadoop_tpu.yarn.client import YarnClient
    from hadoop_tpu.yarn.records import AppState
    from hadoop_tpu.yarn.timeline import TimelineReaderServer

    conf = Configuration(load_defaults=False)
    conf.set("yarn.timeline-service.enabled", "true")
    conf.set("yarn.timeline-service.store.backend", backend)
    store = str(tmp_path / "timeline")
    conf.set("yarn.timeline-service.store.dir", store)    # NM collectors
    conf.set("yarn.timeline-service.store-dir", store)    # RM publisher
    with MiniYARNCluster(num_nodes=2, conf=conf,
                         base_dir=str(tmp_path / "c")) as cluster:
        yc = YarnClient(cluster.rm_addr, cluster.conf)
        try:
            # two apps under ONE name = one flow, same daily run
            app_ids = []
            for _ in range(2):
                a = submit(cluster.rm_addr, ["bash", "-c", "sleep 0.3"],
                           n=2, conf=Configuration(other=cluster.conf),
                           name="nightly-etl")
                report = yc.wait_for_completion(a, timeout=60)
                assert report.state == AppState.FINISHED, \
                    report.diagnostics
                app_ids.append(str(a))
        finally:
            yc.close()

        rconf = Configuration(load_defaults=False)
        reader = TimelineReaderServer(rconf, [store])
        reader.init(rconf)
        reader.start()
        try:
            base = f"http://127.0.0.1:{reader.port}/ws/v2/timeline"

            def get(path):
                return _json.loads(
                    urllib.request.urlopen(base + path).read())

            flows = get("/flows")["flows"]
            assert any(f["flow"] == "nightly-etl" for f in flows)

            runs = get("/flowruns/nightly-etl")["runs"]
            assert len(runs) == 1           # same day → one flow run
            run = runs[0]
            assert sorted(run["apps"]) == sorted(app_ids)
            m = run["metrics"]
            # 2 apps × (1 AM + 2 task containers) finished with metrics
            assert m["containers"] >= 4
            assert m["mb_seconds"] > 0 and m["vcore_seconds"] > 0
            assert m["container_seconds"] > 0

            # per-app cost: the "what did app X cost" question
            app = get(f"/apps/{app_ids[0]}")["app"]
            assert app["metrics"]["mb_seconds"] > 0
            assert app["metrics"]["containers"] >= 2

            # raw entity drill-down
            ents = get(f"/apps/{app_ids[0]}/entities/YARN_CONTAINER")
            assert any(e["event"] == "FINISHED" and
                       "mb_seconds" in e["info"]
                       for e in ents["entities"])
        finally:
            reader.stop()


def test_load_reducer_emits_per_input_record():
    """The traced reduce out/in ratio applies PER INPUT RECORD: a group
    of 100 values at ratio 1.0 emits ~100 records, not 1 (review
    finding), and the CPU burn completes over the task's real record
    count instead of a hard-coded 10k."""
    from hadoop_tpu.tools.gridmix import LoadReducer

    class _Ctx:
        def __init__(self):
            self.conf = {"gridmix.load.reduce.ratio": "1.0",
                         "gridmix.load.reduce.cpu-ms": "0",
                         "gridmix.load.reduce.input-records": "300"}
            self.out = []

        def emit(self, k, v):
            self.out.append((k, v))

    ctx = _Ctx()
    red = LoadReducer()
    red.setup(ctx)
    for g in range(3):
        red.reduce(f"k{g}".encode(), iter([b"v"] * 100), ctx)
    assert len(ctx.out) == 300

    # ratio 0.25 over 400 inputs → 100 outputs
    ctx2 = _Ctx()
    ctx2.conf["gridmix.load.reduce.ratio"] = "0.25"
    red2 = LoadReducer()
    red2.setup(ctx2)
    for g in range(4):
        red2.reduce(f"k{g}".encode(), iter([b"v"] * 100), ctx2)
    assert len(ctx2.out) == 100
