"""NetworkTopology, topology-aware placement/read-order, DN scanners,
NN audit log. Ref: net/NetworkTopology.java,
BlockPlacementPolicyDefault.java, VolumeScanner.java:55,
DirectoryScanner.java:64, FSNamesystem.java:392 (logAuditEvent)."""

import glob
import logging
import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.net import NetworkTopology, TopologyResolver, distance


def test_resolver_table_and_default():
    conf = Configuration(load_defaults=False)
    conf.set("net.topology.table", "h1=/pod0, h2=/pod0, h3=/pod1")
    r = TopologyResolver(conf)
    assert r.resolve("h1") == "/pod0"
    assert r.resolve("h3") == "/pod1"
    assert r.resolve("unknown") == "/default-pod"


def test_distance_and_sort():
    assert distance("/p0", "h1", "/p0", "h1") == 0
    assert distance("/p0", "h1", "/p0", "h2") == 2
    assert distance("/p0", "h1", "/p1", "h2") == 4
    conf = Configuration(load_defaults=False)
    conf.set("net.topology.table", "h1=/pod0,h2=/pod0,h3=/pod1")
    topo = NetworkTopology(TopologyResolver(conf))
    for h in ("h1", "h2", "h3"):
        topo.add(h)

    class N:
        def __init__(self, host):
            self.host = host
    nodes = [N("h3"), N("h2"), N("h1")]
    ordered = topo.sort_by_distance("h1", nodes)
    assert [n.host for n in ordered] == ["h1", "h2", "h3"]
    assert topo.pods() == {"/pod0": ["h1", "h2"], "/pod1": ["h3"]}


def test_placement_spreads_across_pods():
    from hadoop_tpu.dfs.namenode.blockmanager import BlockManager
    from hadoop_tpu.dfs.protocol.records import DatanodeInfo
    conf = Configuration(load_defaults=False)
    conf.set("net.topology.table",
             "hA=/pod0,hB=/pod0,hC=/pod1,hD=/pod1")
    bm = BlockManager(conf)
    dm = bm.dn_manager
    for i, host in enumerate(("hA", "hB", "hC", "hD")):
        dm.register(DatanodeInfo(f"uuid{i}", host, 1000 + i, 2000 + i))
    for trial in range(10):
        targets = dm.choose_targets(3, set(), writer_host="hA")
        assert len(targets) == 3
        assert targets[0].host == "hA"                      # writer-local
        assert targets[1].network_location != "/pod0"       # off-pod
        assert targets[2].network_location == \
            targets[1].network_location                     # same as r2
    # read ordering: reader on hC sees pod1 replicas first
    ordered = dm.sort_by_distance("hC", list(dm._nodes.values()))
    assert ordered[0].host == "hC"
    assert {n.host for n in ordered[:2]} == {"hC", "hD"}


# --------------------------------------------------------------- e2e bits


@pytest.fixture(scope="module")
def cluster():
    from hadoop_tpu.testing.minicluster import MiniDFSCluster
    conf = Configuration(load_defaults=False)
    conf.set("dfs.datanode.scan.period", "0.4s")
    conf.set("dfs.datanode.directoryscan.interval", "0.4s")
    # fast scanners hog the single CI core; don't let a starved heartbeat
    # read as a dead node (same rationale as the benchmark conf)
    conf.set("dfs.heartbeat.interval", "0.3s")
    conf.set("dfs.namenode.heartbeat.recheck-interval", "5s")
    with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
        yield c


def _replica_files(cluster, suffix=""):
    files = glob.glob(os.path.join(cluster.base_dir, "data*", "current",
                                   "finalized", "blk_*" + suffix))
    return [f for f in files if not f.endswith(".meta")]


def test_audit_log_records_namespace_ops(cluster, caplog):
    fs = cluster.get_filesystem()
    with caplog.at_level(logging.INFO, logger="hadoop_tpu.audit"):
        fs.mkdirs("/audit/dir")
        fs.write_all("/audit/f.bin", b"x" * 1000)
        fs.read_all("/audit/f.bin")
        fs.rename("/audit/f.bin", "/audit/g.bin")
        fs.delete("/audit/g.bin")
    lines = [r.getMessage() for r in caplog.records
             if r.name == "hadoop_tpu.audit"]
    cmds = [dict(kv.split("=", 1) for kv in ln.split("\t"))
            for ln in lines]
    by_cmd = {c["cmd"]: c for c in cmds}
    assert {"mkdirs", "create", "open", "rename", "delete"} <= set(by_cmd)
    assert by_cmd["mkdirs"]["src"] == "/audit/dir"
    assert by_cmd["rename"]["dst"] == "/audit/g.bin"
    assert by_cmd["mkdirs"]["allowed"] == "true"
    assert by_cmd["mkdirs"]["ugi"]
    assert by_cmd["mkdirs"]["ip"] not in ("", "local")  # via RPC


def test_volume_scanner_detects_silent_corruption(cluster):
    """Flip bytes in one replica ON DISK (no reads): the volume scanner
    must find it, report it, and the NN re-replicates around it."""
    fs = cluster.get_filesystem()
    fs.write_all("/scan/v.bin", os.urandom(400_000))
    time.sleep(0.3)  # let incremental reports land
    block_id = fs.client.get_block_locations(
        "/scan/v.bin")["blocks"][0]["b"]["id"]
    files = [f for f in _replica_files(cluster)
             if os.path.basename(f) == f"blk_{block_id}"]
    assert files
    victim = sorted(files)[0]
    with open(victim, "r+b") as f:
        f.seek(100)
        f.write(b"\xde\xad\xbe\xef")
    # End state, not transient flags: the scanner reports, the NN
    # invalidates the rotten copy and re-replicates — the victim file is
    # deleted or rewritten with healthy bytes.
    deadline = time.monotonic() + 20
    healed = False
    while time.monotonic() < deadline:
        if not os.path.exists(victim):
            healed = True
            break
        with open(victim, "rb") as f:
            f.seek(100)
            if f.read(4) != b"\xde\xad\xbe\xef":
                healed = True
                break
        time.sleep(0.2)
    assert healed, "rotten replica was never invalidated/re-replicated"
    assert len(fs.read_all("/scan/v.bin")) == 400_000


def test_directory_scanner_detects_vanished_replica(cluster):
    fs = cluster.get_filesystem()
    fs.write_all("/scan/d.bin", os.urandom(200_000))
    time.sleep(0.3)
    files = [f for f in _replica_files(cluster) if "d.bin" or True]
    # find a replica of THIS block: newest files
    newest = max(files, key=os.path.getmtime)
    os.remove(newest)
    os.remove(newest + ".meta")
    deadline = time.monotonic() + 20
    found = False
    while time.monotonic() < deadline:
        # the DN must notice and the NN re-replicate: 3 copies of this
        # block exist again (possibly including a recreated victim path)
        if len([f for f in _replica_files(cluster)
                if os.path.basename(f) == os.path.basename(newest)]) >= 3:
            found = True
            break
        time.sleep(0.3)
    assert found, "vanished replica was never re-replicated"
    assert len(fs.read_all("/scan/d.bin")) == 200_000
