"""The unified telemetry plane: root-decided sampling, the span
collector + flight recorder, /prom exposition, and — the acceptance
path — cross-plane trace assembly: one trace id from the serving door
(resp. the DFS client) through every daemon it touched, pulled back out
of each daemon's ``/ws/v1/traces``.
"""

import http.client
import json
import random
import re
import time

import jax
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.tracing.collector import SpanCollector, span_collector
from hadoop_tpu.tracing.tracer import (SpanContext, Tracer, global_tracer)

# ---------------------------------------------------------------- helpers


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, body
    finally:
        conn.close()


def _get_json(port, path):
    status, body = _get(port, path)
    assert status == 200, body
    return json.loads(body)


def _trace_names(port, trace_id):
    """Span names for one trace id, pulled from a daemon's collector."""
    snap = _get_json(port, f"/ws/v1/traces?trace_id={trace_id}")
    return {s["name"] for s in snap["spans"]}


def _abrupt_stream_client(port, method, path, body=b""):
    """Open a RAW socket request and return (sock, first_chunk). The
    caller kills it with _rst_close — http.client keeps the fd alive
    through the response object, which can't model a crashed client."""
    import socket
    sock = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    req = (f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
           f"Content-Length: {len(body)}\r\n"
           "Content-Type: application/json\r\n\r\n").encode() + body
    sock.sendall(req)
    first = sock.recv(65536)
    return sock, first


def _rst_close(sock):
    """Close with SO_LINGER=0: an immediate RST, like a killed client —
    the server's next write fails instead of filling buffers forever."""
    import socket as _s
    import struct
    sock.setsockopt(_s.SOL_SOCKET, _s.SO_LINGER,
                    struct.pack("ii", 1, 0))
    sock.close()


# ----------------------------------------------------- sampling (all-or-none)


def test_sampling_decided_at_root_is_all_or_nothing():
    """Regression for the per-span coin flip: at sample_rate < 1 every
    trace must be delivered whole or not at all — including spans
    resumed from a wire context on 'another process'."""
    tr = Tracer(sample_rate=0.5, rng=random.Random(7))
    for _ in range(60):
        with tr.span("root") as root:
            with tr.span("child"):
                pass
            # remote hop: resume via the serialized wire context
            ctx = SpanContext.from_wire(root.context().to_wire())
            tr.span("remote", parent=ctx).finish()
    by_trace = {}
    for s in tr.finished:
        by_trace.setdefault(s.trace_id, []).append(s.name)
    assert 0 < len(by_trace) < 60          # some kept, some dropped
    for names in by_trace.values():
        assert sorted(names) == ["child", "remote", "root"], \
            "a sampled trace was shredded"


def test_sample_rate_zero_drops_remote_children_too():
    tr = Tracer(sample_rate=0.0)
    root = tr.span("root")
    ctx = SpanContext.from_wire(root.context().to_wire())
    assert ctx.sampled is False
    tr.span("remote", parent=ctx).finish()
    root.finish()
    assert tr.finished == []


def test_wire_context_without_sampled_bit_defaults_to_sampled():
    # pre-upgrade peers send {"t","s"} only
    ctx = SpanContext.from_wire({"t": 1, "s": 2})
    assert ctx.sampled is True


def test_header_roundtrip():
    ctx = SpanContext(0xdeadbeef, 0x1234, False)
    back = SpanContext.from_header(ctx.to_header())
    assert (back.trace_id, back.span_id, back.sampled) == \
        (0xdeadbeef, 0x1234, False)
    assert SpanContext.from_header("") is None
    assert SpanContext.from_header("garbage") is None


def test_carry_context_parents_across_threads():
    import threading
    from hadoop_tpu.tracing.tracer import carry_context
    tr = Tracer()
    got = {}

    def work():
        sp = tr.span("inner")
        got["trace"] = sp.trace_id
        sp.finish()

    with tr.span("outer") as outer:
        t = threading.Thread(target=carry_context(work))
        t.start()
        t.join()
    assert got["trace"] == outer.trace_id


# ------------------------------------------------------- collector + flight


def test_collector_ring_bounds_and_drop_counter():
    col = SpanCollector(max_spans=8, max_traces=4)
    tr = Tracer()
    tr.add_receiver(col.receive)
    for i in range(20):
        tr.span(f"op{i}").finish()
    snap = col.snapshot()
    assert len(snap["spans"]) == 8
    assert snap["dropped"] == 12
    assert snap["spans"][-1]["name"] == "op19"


def test_flight_recorder_promotes_whole_slow_trace():
    col = SpanCollector()
    conf = Configuration(load_defaults=False)
    conf.set("tracing.slow.rpc.ms", "5")
    col.configure(conf)
    tr = Tracer()
    tr.add_receiver(col.receive)
    with tr.span("namenode.slow_op") as root:
        tr.span("namenode.fast_child").finish()   # fast: not a trigger
        time.sleep(0.02)                          # root crosses 5 ms
    slow = col.slow_traces()
    assert slow["promoted"] == 1
    trace = slow["traces"][0]
    assert trace["trigger"] == "namenode.slow_op"
    assert trace["trigger_ms"] >= 5
    # the WHOLE trace was retained, not just the trigger span
    names = {s["name"] for s in trace["spans"]}
    assert names == {"namenode.slow_op", "namenode.fast_child"}
    assert trace["trace_id"] == root.trace_id


def test_slow_thresholds_are_conf_keyed_per_plane():
    col = SpanCollector()
    conf = Configuration(load_defaults=False)
    conf.set("tracing.slow.xceiver.ms", "123")
    conf.set("tracing.slow.step.ms", "456")
    conf.set("tracing.slow.serving.ms", "789")
    conf.set("tracing.slow.rpc.ms", "42")
    col.configure(conf)
    assert col.threshold_ms_for("dfs.xceiver.read_block") == 123
    assert col.threshold_ms_for("trainer.step") == 456
    assert col.threshold_ms_for("serving.request") == 789
    assert col.threshold_ms_for("namenode.mkdirs") == 42
    # long-by-design bulk spans have their own (lenient) rules — they
    # must NOT fall through to the 42 ms RPC catch-all
    assert col.threshold_ms_for("trainer.ckpt.write") == 30000
    assert col.threshold_ms_for("dfs.client.read") == 2000
    # reset restores defaults: a test's near-zero threshold can't leak
    col.reset_for_tests()
    assert col.threshold_ms_for("namenode.mkdirs") == 300


def test_traces_endpoint_accepts_hex_and_decimal_trace_ids():
    """The slow-trace log line and X-Htpu-Trace header print hex; the
    query must accept that form (and plain decimal) or the
    grep-the-log-then-query workflow dead-ends."""
    from hadoop_tpu.http.server import HttpServer
    tracer = global_tracer()
    with tracer.span("probe.op") as sp:
        pass
    srv = HttpServer(Configuration(load_defaults=False), daemon_name="t")
    srv.start()
    try:
        for form in (str(sp.trace_id), f"{sp.trace_id:016x}",
                     f"0x{sp.trace_id:x}"):
            snap = _get_json(srv.port, f"/ws/v1/traces?trace_id={form}")
            assert any(s["name"] == "probe.op" for s in snap["spans"]), \
                f"form {form!r} found nothing"
        status, _ = _get(srv.port, "/ws/v1/traces?trace_id=zzz")
        assert status == 400
    finally:
        srv.stop()


# ----------------------------------------------------------- /prom parsing

_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? "
    r"(?:[-+]?[0-9.eE+-]+|\+Inf|-Inf|NaN)"
    # optional OpenMetrics exemplar on histogram _bucket lines
    r"(?: # \{[^}]*\} [-+]?[0-9.eE+-]+(?: [0-9.]+)?)?)$")


def _assert_parseable_prom(text):
    assert text.strip(), "empty /prom body"
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"unparseable prom line: {line!r}"
    types = dict(re.findall(r"# TYPE (\S+) (\S+)", text))
    return types


# --------------------------------------------------- miniDFS: one trace id


def test_minidfs_one_trace_across_planes_and_prom(tmp_path):
    """The DFS acceptance path, one cluster: (1) a single block read
    under one client root span yields ONE trace_id whose spans cover
    the client read, the NameNode RPC handler, and the DataNode
    xceiver — verified by pulling /ws/v1/traces from every daemon's
    HTTP server; (2) a pipelined write joins the client trace the same
    way; (3) /prom on both daemons is parseable and carries counters,
    gauges, and the new log-bucketed histograms."""
    from hadoop_tpu.testing.minicluster import MiniDFSCluster
    conf = Configuration(load_defaults=False)
    conf.set("dfs.replication", "1")
    # force the remote (TCP xceiver) read path — short-circuit would
    # bypass the DN entirely and there'd be no DN hop to trace
    conf.set("dfs.client.read.shortcircuit", "false")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path / "traced")) as cluster:
        fs = cluster.get_filesystem()
        tracer = global_tracer()
        nn_port = cluster.namenode.http.port
        dn_port = cluster.datanodes[0].http.port

        # ---- write: the pipeline setup frame carries the context
        span_collector().reset_for_tests()
        with tracer.span("fsshell.put") as wroot:
            payload = b"traced-bytes" * 1000
            with fs.create("/traced.bin") as out:
                out.write(payload)
        wnames = _trace_names(dn_port, wroot.trace_id)
        assert "dfs.xceiver.write_block" in wnames
        snap = _get_json(dn_port,
                         f"/ws/v1/traces?trace_id={wroot.trace_id}")
        wr = [s for s in snap["spans"]
              if s["name"] == "dfs.xceiver.write_block"][0]
        assert wr["kv"]["crc_ok"] == "true"
        assert wr["kv"]["pipeline_remaining"] == "0"  # single-DN chain

        # ---- read: ONE assembled trace across all three planes
        with tracer.span("fsshell.cat") as root:   # the client-side root
            assert fs.read_all("/traced.bin") == payload
        trace_id = root.trace_id
        # every daemon's collector (one per process; the minicluster's
        # daemons share this process) shows the SAME assembled trace
        for port in (nn_port, dn_port):
            names = _trace_names(port, trace_id)
            # plane 1: client
            assert "fsshell.cat" in names
            assert "dfs.client.read" in names
            # plane 2: NN RPC handler (resumed from the RPC header)
            assert any(n.startswith("namenode.") for n in names), names
            # plane 3: DN xceiver (resumed from the op frame header)
            assert "dfs.xceiver.read_block" in names
        # the xceiver annotated data-plane facts onto the client trace
        snap = _get_json(dn_port, f"/ws/v1/traces?trace_id={trace_id}")
        xc = [s for s in snap["spans"]
              if s["name"] == "dfs.xceiver.read_block"]
        assert xc and int(xc[0]["kv"]["bytes"]) > 0

        # ---- /prom on both daemons
        for port in (nn_port, dn_port):
            status, body = _get(port, "/prom")
            assert status == 200
            types = _assert_parseable_prom(body.decode())
            assert {"counter", "gauge", "histogram"} <= \
                set(types.values()), types
        _, body = _get(dn_port, "/prom")
        text = body.decode()
        assert "htpu_read_block_seconds_bucket" in text
        assert 'le="+Inf"' in text


# ------------------------------------------- serving: one trace id + /prom


@pytest.fixture(scope="module")
def tiny_model():
    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import init_params
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def test_router_to_replica_generate_is_one_trace(tiny_model):
    """router → replica door → engine admit → first token all share the
    request's trace id (header-propagated), pulled from the replica's
    /ws/v1/traces; the flight recorder retains the trace when the
    serving threshold trips."""
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    from hadoop_tpu.serving.engine import DecodeEngine
    from hadoop_tpu.serving.metrics import ServingMetrics
    from hadoop_tpu.serving.router import ServingRouter, replica_path
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    # any serving.request span longer than 0.01 ms trips the recorder
    conf.set("tracing.slow.serving.ms", "0.01")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    # reset BEFORE the replica configures the collector: reset restores
    # default thresholds, which would undo the 0.01 ms one above
    span_collector().reset_for_tests()
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, metrics=ServingMetrics())
    srv = ServingServer(eng, conf)
    eng.start()
    srv.start()
    assert span_collector().threshold_ms_for("serving.request") == 0.01
    router = None
    try:
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        rc.register(ServiceRecord(
            replica_path("traced", "r0"),
            {"http": f"127.0.0.1:{srv.port}"},
            {"state": "serving"}), ttl_s=30.0, auto_renew=False)
        router = ServingRouter(reg_addr, "traced", conf, cache_ttl_s=0.0)
        out = router.generate({"tokens": [3, 4, 5], "max_new_tokens": 4})
        assert len(out["tokens"]) == 4

        # the router span is the root; find it in the local tracer
        roots = [s for s in global_tracer().finished
                 if s.name == "serving.router.generate"]
        assert roots, "router did not emit its root span"
        trace_id = roots[-1].trace_id
        names = _trace_names(srv.port, trace_id)
        assert {"serving.router.generate", "serving.request",
                "serving.admit", "serving.first_token"} <= names, names

        # flight recorder: the serving.request span crossed 0.01 ms
        slow = _get_json(srv.port, "/ws/v1/traces/slow")
        assert any(t["trace_id"] == trace_id for t in slow["traces"])

        # /prom on the replica: counters + gauges + histograms
        status, body = _get(srv.port, "/prom")
        assert status == 200
        types = _assert_parseable_prom(body.decode())
        assert {"counter", "gauge", "histogram"} <= set(types.values())
        assert "htpu_decode_step_seconds_bucket" in body.decode()
        rc.close()
    finally:
        if router is not None:
            router.close()
        srv.stop()
        reg_srv.stop()


def test_stream_span_finishes_on_client_disconnect(tiny_model):
    """Satellite regression: a client that abandons a stream mid-flight
    must still finish the door's serving.request span (the chassis
    close()s the abandoned generator; its finally finishes the span)."""
    from hadoop_tpu.serving.engine import DecodeEngine
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=128)
    srv = ServingServer(eng, Configuration(load_defaults=False))
    eng.start()
    srv.start()
    try:
        before = len(global_tracer().finished)
        body = json.dumps({"tokens": [3, 4, 5], "max_new_tokens": 120,
                           "stream": True}).encode()
        sock, first = _abrupt_stream_client(srv.port, "POST",
                                            "/v1/generate", body)
        assert b"200" in first.split(b"\r\n", 1)[0]
        _rst_close(sock)                  # crash mid-stream
        deadline = time.monotonic() + 15.0
        finished = []
        while time.monotonic() < deadline:
            finished = [s for s in global_tracer().finished[before:]
                        if s.name == "serving.request"]
            if finished:
                break
            time.sleep(0.05)
        assert finished, ("serving.request span leaked after client "
                          "disconnect")
    finally:
        srv.stop()


def test_failed_generation_returns_500_and_delivers_span(tiny_model):
    """A request the engine FAILS (stop/drain, decode error) must still
    deliver the serving.request span — the failure path is where the
    cross-daemon trace earns its keep."""
    from hadoop_tpu.serving.engine import DecodeEngine
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    srv = ServingServer(eng, Configuration(load_defaults=False))
    # engine deliberately NOT started: stop() fails whatever is queued
    before = len(global_tracer().finished)
    result = {}

    def call():
        result["out"] = srv._generate(
            {"__trace__": "", "__user__": "t"},
            json.dumps({"tokens": [1, 2, 3],
                        "max_new_tokens": 2}).encode())

    import threading
    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.2)          # the request is parked in the queue
    eng.stop()               # fails it: wait() raises RuntimeError
    t.join(10.0)
    status, payload = result["out"]
    assert status == 500
    assert "GenerationFailed" in payload["RemoteException"]["exception"]
    finished = [s for s in global_tracer().finished[before:]
                if s.name == "serving.request"]
    assert finished and "failed" in finished[0].kv
    srv.stop()


def test_http_chassis_closes_abandoned_generator():
    """Chassis-level: a streaming payload generator abandoned by a
    dying connection runs its cleanup immediately (not at GC)."""
    import threading
    from hadoop_tpu.http.server import HttpServer
    cleaned = threading.Event()

    def gen():
        try:
            while True:
                yield b"x" * 65536
                time.sleep(0.01)
        finally:
            cleaned.set()

    http_srv = HttpServer(Configuration(load_defaults=False),
                          daemon_name="t")
    http_srv.add_handler("/stream", lambda q, b: (200, gen()))
    http_srv.start()
    try:
        sock, first = _abrupt_stream_client(http_srv.port, "GET",
                                            "/stream")
        assert first
        _rst_close(sock)
        assert cleaned.wait(10.0), "generator cleanup never ran"
    finally:
        http_srv.stop()


# ------------------------------------------------ trainer anatomy metrics


@pytest.mark.slow
def test_trainer_step_anatomy_is_live():
    """Per-step metrics + spans: data-wait/step-wall rates tick, the
    ckpt snapshot/write/fence split records, and trainer.step spans
    reach the collector."""
    import numpy as np
    from hadoop_tpu.fs import FileSystem
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.parallel.mesh import MeshPlan
    from hadoop_tpu.parallel.trainer import Trainer
    import tempfile
    cfg = get_config("tiny")
    td = tempfile.mkdtemp(prefix="anatomy-")
    fs = FileSystem.get(f"file://{td}")
    tokens = np.random.randint(0, cfg.vocab_size, size=(4096,),
                               ).astype("uint16")
    with open(f"{td}/data.bin", "wb") as f:
        f.write(tokens.tobytes())
    tr = Trainer(cfg, MeshPlan(), fs, f"{td}/data.bin", f"{td}/ckpt",
                 batch=2, ckpt_interval=2)
    span_collector().reset_for_tests()
    tr.train(3)
    tr.wait_for_checkpoint()
    snap = metrics_system().source("trainer").snapshot()
    assert snap["steps"] == 3
    assert snap["step_wall_num_ops"] == 3
    assert snap["data_wait_num_ops"] == 3
    assert snap["ckpt_snapshot_num_ops"] >= 1   # the interval save
    names = [s["name"] for s in span_collector().snapshot()["spans"]]
    assert names.count("trainer.step") == 3
    assert "trainer.ckpt.snapshot" in names
    assert "trainer.ckpt.write" in names
    # the async write span joined the step's trace (carried context)
    spans = span_collector().snapshot()["spans"]
    write_sp = [s for s in spans if s["name"] == "trainer.ckpt.write"][0]
    step_traces = {s["trace_id"] for s in spans
                   if s["name"] == "trainer.step"}
    assert write_sp["trace_id"] in step_traces
