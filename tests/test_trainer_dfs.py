"""Trainer ⇄ DFS integration: sharded checkpoints + streaming dataloader.

The acceptance bar (VERDICT r2 item 3): kill a training run mid-stream,
resume from the DFS checkpoint, and the loss curve continues EXACTLY as
an uninterrupted run — params, optimizer moments, and the data cursor all
round-trip through the framework's own storage layer.
"""

import numpy as np
import pytest

import jax

from hadoop_tpu.models import get_config
from hadoop_tpu.parallel import MeshPlan
from hadoop_tpu.parallel.checkpoint import (latest_step, list_checkpoints,
                                            load_checkpoint,
                                            save_checkpoint)
from hadoop_tpu.testing.minicluster import MiniDFSCluster

BATCH = 8


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


@pytest.fixture(scope="module")
def token_file(fs):
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 256, 200_000, dtype=np.uint16)
    fs.mkdirs("/data")
    fs.write_all("/data/tokens.bin", toks.tobytes())
    return "/data/tokens.bin"


def _trainer(fs, token_file, ckpt_dir, zero1=False, interval=0):
    from hadoop_tpu.parallel.trainer import Trainer
    cfg = get_config("tiny")
    return Trainer(cfg, MeshPlan(dp=2, tp=2), fs, token_file, ckpt_dir,
                   batch=BATCH, lr=1e-2, optimizer="adamw", zero1=zero1,
                   ckpt_interval=interval)


def test_resume_continues_loss_curve_exactly(fs, token_file):
    # uninterrupted 6-step run
    ref = _trainer(fs, token_file, "/ckpt/ref")
    ref_losses = ref.train(6)

    # crashed run: 3 steps, checkpoint, new process (fresh Trainer), resume
    a = _trainer(fs, token_file, "/ckpt/crash")
    a_losses = a.train(3)
    a.save()
    del a

    b = _trainer(fs, token_file, "/ckpt/crash")
    assert b.try_restore()
    assert b.step == 3
    b_losses = b.train(3)

    np.testing.assert_allclose(a_losses, ref_losses[:3], rtol=1e-6)
    np.testing.assert_allclose(b_losses, ref_losses[3:], rtol=1e-6)


def test_resume_zero1_state_roundtrip(fs, token_file):
    a = _trainer(fs, token_file, "/ckpt/z1", zero1=True)
    a_losses = a.train(4)
    a.save()

    b = _trainer(fs, token_file, "/ckpt/z1", zero1=True)
    assert b.try_restore()
    b_losses = b.train(2)

    ref = _trainer(fs, token_file, "/ckpt/z1ref", zero1=True)
    ref_losses = ref.train(6)
    np.testing.assert_allclose(a_losses + b_losses, ref_losses, rtol=1e-6)


def test_checkpoint_resharding_across_plans(fs, token_file):
    """A checkpoint saved under dp2×tp2 loads into dp4 (and back) — the
    global-value manifest makes resharding at load free."""
    from hadoop_tpu.parallel.trainer import Trainer
    cfg = get_config("tiny")
    t1 = Trainer(cfg, MeshPlan(dp=2, tp=2), fs, token_file, "/ckpt/rs",
                 batch=BATCH, lr=1e-2, ckpt_interval=0)
    t1.train(2)
    t1.save()
    expect = jax.tree_util.tree_map(np.asarray, jax.device_get(t1.params))

    t2 = Trainer(cfg, MeshPlan(dp=4), fs, token_file, "/ckpt/rs",
                 batch=BATCH, lr=1e-2, ckpt_interval=0)
    assert t2.try_restore()
    got = jax.tree_util.tree_map(np.asarray, jax.device_get(t2.params))
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(expect),
            jax.tree_util.tree_leaves_with_path(got)):
        np.testing.assert_array_equal(a, b, err_msg=str(pa))


def test_checkpoint_retention_and_atomicity(fs, token_file):
    a = _trainer(fs, token_file, "/ckpt/keep", interval=1)
    a.keep = 2
    a.train(5)
    steps = list_checkpoints(fs, "/ckpt/keep")
    assert steps == [4, 5]
    assert latest_step(fs, "/ckpt/keep") == 5
    # a torn tmp dir is never listed as a checkpoint
    fs.mkdirs("/ckpt/keep/step_000000000099._tmp")
    assert latest_step(fs, "/ckpt/keep") == 5


def test_save_load_plain_tree(fs):
    tree = {"a": jax.numpy.arange(12, dtype=jax.numpy.float32)
            .reshape(3, 4), "n": jax.numpy.zeros((), jax.numpy.int32)}
    save_checkpoint(fs, "/ckpt/plain", 7, tree)
    like = {"a": np.zeros((3, 4), np.float32),
            "n": np.zeros((), np.int32)}
    out, step = load_checkpoint(fs, "/ckpt/plain", like)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(out["a"]),
                                  np.asarray(tree["a"]))


def test_dataloader_state_roundtrip(fs, token_file):
    from hadoop_tpu.parallel.data import TokenDataset
    d1 = TokenDataset(fs, token_file, batch=4, seq=32)
    first = [d1.next_batch() for _ in range(3)]
    st = d1.state()
    nxt = d1.next_batch()

    d2 = TokenDataset(fs, token_file, batch=4, seq=32)
    d2.restore(st)
    np.testing.assert_array_equal(d2.next_batch(), nxt)

    # deterministic from the start too
    d3 = TokenDataset(fs, token_file, batch=4, seq=32)
    np.testing.assert_array_equal(d3.next_batch(), first[0])


def test_trainer_checkpoints_to_object_store():
    """The checkpoint layer rides the FileSystem SPI, so the S3A-analog
    object store works as a checkpoint target unmodified (the cloud
    training story: params in object storage, not just the DFS)."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.fs import FileSystem
    from hadoop_tpu.parallel import make_mesh
    from hadoop_tpu.parallel.train import init_sharded
    from hadoop_tpu.testing.fakestore import FakeObjectStore

    with FakeObjectStore() as store:
        fs = FileSystem.get(f"htps://{store.endpoint}/bkt",
                            Configuration(load_defaults=False))
        cfg = get_config("tiny")
        plan = MeshPlan()
        mesh = make_mesh(plan)
        params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan,
                                   mesh)
        save_checkpoint(fs, "/ckpt", 7, {"params": params, "opt": opt})
        assert latest_step(fs, "/ckpt") == 7
        like = {"params": params, "opt": opt}
        loaded, step = load_checkpoint(fs, "/ckpt", like, step=7)
        assert step == 7
        for a, b in zip(jax.tree_util.tree_leaves(loaded),
                        jax.tree_util.tree_leaves(like), strict=True):
            assert (a == b).all()


def test_trainer_builds_pipeline_plans(fs, token_file):
    """Trainer resolves n_microbatches for pp/vpp plans instead of
    crashing at first trace (review finding: vpp>1 raised ValueError,
    pp>1 silently ran a full-bubble single microbatch)."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.trainer import Trainer

    cfg = get_config("tiny")
    t = Trainer(cfg, MeshPlan(dp=4, pp=2), fs, token_file,
                "/ckpt-pp", batch=BATCH, ckpt_interval=0)
    losses = t.train(2)
    assert len(losses) == 2 and all(l == l for l in losses)  # no NaN


def test_trainer_cursor_survives_past_int32(fs, token_file, tmp_path):
    """The data cursor checkpoints as two int32 halves: a position past
    2**31 (ordinary LM-scale datasets) must round-trip exactly (review
    finding: a single int32 wrapped negative and resumed the stream
    ~1.8e9 tokens off)."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.trainer import Trainer

    cfg = get_config("tiny")
    t = Trainer(cfg, MeshPlan(dp=8), fs, token_file, "/ckpt-big",
                batch=BATCH, ckpt_interval=0)
    big = 3_000_000_123
    t.data.total_tokens = big + 500_000  # pretend at-scale dataset
    t.data._pos = big
    t.step = 7
    t.save()
    t2 = Trainer(cfg, MeshPlan(dp=8), fs, token_file, "/ckpt-big",
                 batch=BATCH, ckpt_interval=0)
    t2.data.total_tokens = big + 500_000
    assert t2.try_restore()
    assert t2.data.state()["pos"] == big


def test_incomplete_checkpoint_is_invisible_and_swept(fs, token_file):
    """A crashed publish (shards, no manifest) must be invisible to
    restore and swept by the next save (review finding: the rename-based
    publish could expose a manifest-complete checkpoint with missing
    shards on object stores)."""
    from hadoop_tpu.parallel.checkpoint import latest_step

    cfg = get_config("tiny")
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.trainer import Trainer

    t = Trainer(cfg, MeshPlan(dp=8), fs, token_file, "/ckpt-crash",
                batch=BATCH, ckpt_interval=0)
    t.step = 5
    t.save()
    # fabricate a crashed newer publish: shard but no manifest
    fs.mkdirs("/ckpt-crash/step_000000000009")
    fs.write_all("/ckpt-crash/step_000000000009/shard_000000.bin",
                 b"\x00" * 64)
    assert latest_step(fs, "/ckpt-crash") == 5  # invisible
    t2 = Trainer(cfg, MeshPlan(dp=8), fs, token_file, "/ckpt-crash",
                 batch=BATCH, ckpt_interval=0)
    assert t2.try_restore() and t2.step == 5
    t2.step = 11
    t2.save()  # retention sweep removes the orphan
    assert not fs.exists("/ckpt-crash/step_000000000009")


def test_mid_run_interval_checkpoint_resumes_exactly(fs, token_file):
    """A checkpoint taken INSIDE train() (interval save) while the
    prefetch thread has read ahead must record the cursor of the last
    consumed batch, not the dataset's advanced position — resume from it
    continues the reference loss curve exactly."""
    ref = _trainer(fs, token_file, "/ckpt/mid-ref")
    ref_losses = ref.train(6)

    a = _trainer(fs, token_file, "/ckpt/mid", interval=3)
    a.train(4)  # interval save fires at step 3 with a batch in flight

    b = _trainer(fs, token_file, "/ckpt/mid")
    assert b.try_restore()
    assert b.step == 3
    b_losses = b.train(3)
    np.testing.assert_allclose(b_losses, ref_losses[3:], rtol=1e-6)
