"""ViewFs client-side mount tables.

Mirrors the reference's viewfs tests (ref: hadoop-common
TestViewFileSystemHdfs.java — a view over live namespaces;
TestViewFsConfig.java — link config parsing): a view spanning TWO
live DFS namespaces plus an object store.
"""

import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.fs.viewfs import ViewFileSystem
from hadoop_tpu.testing.fakestore import FakeObjectStore
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


@pytest.fixture(scope="module")
def two_clusters(tmp_path_factory):
    base = tmp_path_factory.mktemp("viewfs")
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(base / "c1")) as c1, \
            MiniDFSCluster(num_datanodes=1, conf=conf,
                           base_dir=str(base / "c2")) as c2:
        c1.wait_active()
        c2.wait_active()
        yield c1, c2


def _view_conf(c1, c2, store=None):
    conf = Configuration(load_defaults=False)
    conf.set("fs.viewfs.mounttable.test.link./data", f"{c1.default_fs}/data")
    conf.set("fs.viewfs.mounttable.test.link./logs", f"{c2.default_fs}/logs")
    if store is not None:
        conf.set("fs.viewfs.mounttable.test.link./cold",
                 f"htps://{store.endpoint}/bkt/cold")
    return conf


def test_view_spans_two_namespaces(two_clusters):
    c1, c2 = two_clusters
    view = FileSystem.get("viewfs://test/", _view_conf(c1, c2))
    assert isinstance(view, ViewFileSystem)
    a, b = os.urandom(10_000), os.urandom(5_000)
    view.write_all("/data/a.bin", a)
    view.write_all("/logs/app/b.log", b)
    # each landed on its OWN cluster
    assert c1.get_filesystem().read_all("/data/a.bin") == a
    assert c2.get_filesystem().read_all("/logs/app/b.log") == b
    # and reads resolve back through the view
    assert view.read_all("/data/a.bin") == a
    assert view.read_all("/logs/app/b.log") == b
    st = view.get_file_status("/logs/app/b.log")
    assert st.length == len(b) and not st.is_dir


def test_view_root_lists_mount_points(two_clusters):
    c1, c2 = two_clusters
    view = FileSystem.get("viewfs://test/", _view_conf(c1, c2))
    roots = {s.path for s in view.list_status("/")}
    assert roots == {"/data", "/logs"}
    for s in view.list_status("/"):
        assert s.is_dir


def test_view_listing_translates_paths(two_clusters):
    c1, c2 = two_clusters
    view = FileSystem.get("viewfs://test/", _view_conf(c1, c2))
    view.mkdirs("/data/sub")
    view.write_all("/data/sub/x", b"x")
    view.write_all("/data/y", b"y")
    names = {s.path for s in view.list_status("/data")}
    assert "/data/sub" in names and "/data/y" in names
    assert {s.path for s in view.list_status("/data/sub")} == {"/data/sub/x"}


def test_view_rename_within_and_across_mounts(two_clusters):
    c1, c2 = two_clusters
    view = FileSystem.get("viewfs://test/", _view_conf(c1, c2))
    view.write_all("/data/mv-src", b"m")
    assert view.rename("/data/mv-src", "/data/mv-dst")
    assert view.read_all("/data/mv-dst") == b"m"
    with pytest.raises(IOError, match="across mount points"):
        view.rename("/data/mv-dst", "/logs/mv-dst")


def test_view_includes_object_store(two_clusters):
    c1, c2 = two_clusters
    with FakeObjectStore() as store:
        view = FileSystem.get("viewfs://test/",
                              _view_conf(c1, c2, store))
        data = os.urandom(20_000)
        view.write_all("/cold/archive/f.bin", data)
        assert view.read_all("/cold/archive/f.bin") == data
        sfs = FileSystem.get(f"htps://{store.endpoint}/bkt",
                             Configuration())
        assert sfs.read_all("/bkt/cold/archive/f.bin") == data


def test_unmounted_path_rejected(two_clusters):
    c1, c2 = two_clusters
    view = FileSystem.get("viewfs://test/", _view_conf(c1, c2))
    with pytest.raises(FileNotFoundError, match="mount point"):
        view.open("/nowhere/file")


def test_multilevel_mounts_walkable(two_clusters):
    """Internal mount-tree nodes list their children so recursive walks
    (distcp, ls -R) work above the links."""
    c1, c2 = two_clusters
    conf = Configuration(load_defaults=False)
    conf.set("fs.viewfs.mounttable.ml.link./data/warehouse",
             f"{c1.default_fs}/wh")
    conf.set("fs.viewfs.mounttable.ml.link./data/logs",
             f"{c2.default_fs}/lg")
    view = FileSystem.get("viewfs://ml/", conf)
    view.write_all("/data/warehouse/t1", b"w")
    view.write_all("/data/logs/l1", b"l")
    assert view.get_file_status("/data").is_dir
    level1 = {s.path for s in view.list_status("/")}
    assert level1 == {"/data"}
    level2 = {s.path for s in view.list_status("/data")}
    assert level2 == {"/data/warehouse", "/data/logs"}
    assert {s.path for s in view.list_status("/data/warehouse")} \
        == {"/data/warehouse/t1"}


def test_nested_mount_visible_in_parent_listing():
    """A mount nested under another mount appears in the parent mount's
    listing — recursive walks must not silently skip its subtree
    (review finding)."""
    from hadoop_tpu.fs.viewfs import ViewFileSystem
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    with MiniDFSCluster(num_datanodes=1, conf=fast_conf()) as c1, \
            MiniDFSCluster(num_datanodes=1, conf=fast_conf()) as c2:
        c1.wait_active()
        c2.wait_active()
        c1.get_filesystem().mkdirs("/data")
        c2.get_filesystem().mkdirs("/archive")
        vconf = Configuration(load_defaults=False)
        vconf.set("fs.viewfs.mounttable.cl.link./data",
                  f"{c1.default_fs}/data")
        vconf.set("fs.viewfs.mounttable.cl.link./data/archive",
                  f"{c2.default_fs}/archive")
        v = ViewFileSystem(vconf, table="cl")
        names = {s.path.rsplit("/", 1)[-1]
                 for s in v.list_status("/data")}
        assert "archive" in names
        # and the nested subtree resolves through the second cluster
        v.mkdirs("/data/archive/deep")
        assert c2.get_filesystem().exists("/archive/deep")
