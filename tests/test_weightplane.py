"""The serving weight plane (serving/weightplane.py): int8-resident
weights behind the ``serving.parity`` tier.

Pins the four contracts the tier ships under:

- the weight codec is the ONE public per-group int8 quantizer
  (``parallel.lowp.quantize_array``) with a loud shape/group contract
  and an SQNR floor on realistic weight distributions;
- ``serving.parity=bitwise`` (the default) is byte-identical serving:
  raw params, zero quantized code reachable, greedy tokens equal to
  the full-recompute reference;
- the relaxed tier's greedy outputs are accepted by the logits/output
  A-B guard with the compile-once contract intact, and the freed HBM
  converts into >= 2x lanes x context at a fixed budget;
- quantize-at-load streams per shard: peak host f32 bytes stay
  bounded below the full model, and the streamed tree is bit-identical
  to the in-memory policy application.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import forward, init_params
from hadoop_tpu.serving import weightplane as wp
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


FULL_POLICY = wp.WeightPlaneConfig(tier="relaxed", group=16,
                                   quant_embed=True, quant_head=True)


# ----------------------------------------------------- the weight codec

def test_weight_codec_sqnr_floor_on_winit_distributions():
    """Per-group int8 round-trip keeps >= 35 dB SQNR on the fan-in
    scaled gaussians ``init_params`` actually draws — via the PUBLIC
    lowp API (the promotion: one quantizer defines every int8
    surface)."""
    from hadoop_tpu.parallel.lowp import dequantize_array, quantize_array
    rng = np.random.default_rng(7)
    for fan_in, shape in ((64, (64, 128)), (128, (128, 64)),
                          (256, (256, 64))):
        x = rng.normal(0, fan_in ** -0.5, size=shape).astype(np.float32)
        for group in (8, 16, 64):
            q, s = quantize_array(x, group=group)
            y = dequantize_array(q, s, x.shape, np.float32)
            sqnr = 10 * np.log10(float((x ** 2).mean()) /
                                 float(((x - y) ** 2).mean()))
            assert sqnr >= 35.0, (fan_in, group, sqnr)
            assert s.size == -(-x.size // group)


def test_weight_codec_zeros_exact_and_scale_shape_contract():
    arr = np.zeros((2, 32, 48), np.float32)          # [L, D, N] weight
    qw = wp.quantize_weight(arr, 16, transpose=True)
    # transposed-and-grouped layout: [L, N, G, gs] + [L, N, G]
    assert qw["q"].shape == (2, 48, 2, 16)
    assert qw["q"].dtype == np.int8
    assert qw["s"].shape == (2, 48, 2)
    assert qw["s"].dtype == np.float32
    back = wp.dequantize_weight(qw, transpose=True)
    assert back.shape == arr.shape
    assert np.array_equal(back, arr)                 # zeros decode EXACT
    # realistic values round-trip allclose with the axes restored
    rng = np.random.default_rng(0)
    arr = rng.normal(0, 0.1, size=(2, 32, 48)).astype(np.float32)
    qw = wp.quantize_weight(arr, 16, transpose=True)
    back = wp.dequantize_weight(qw, transpose=True)
    assert np.allclose(back, arr, atol=2e-3)


def test_weight_codec_group_and_shape_mismatch_is_loud():
    # a contraction dim the group does not divide raises instead of
    # silently regrouping across rows (16 does not divide 60)
    arr = np.zeros((2, 60, 48), np.float32)   # transpose -> 60 last
    with pytest.raises(ValueError, match="group"):
        wp.quantize_weight(arr, 16, transpose=True)
    with pytest.raises(ValueError, match="group"):
        wp.quantize_weight(np.zeros((2, 48, 60), np.float32), 16,
                           transpose=False)
    # a scale plane that does not match the payload is a loud error,
    # never a silent dequantization against the wrong scales
    qw = wp.quantize_weight(np.zeros((4, 32), np.float32), 16,
                            transpose=False)
    qw_bad = {"q": qw["q"], "s": qw["s"][:2]}
    with pytest.raises(ValueError, match="scale"):
        wp.dequantize_weight(qw_bad, transpose=False)


def test_policy_table_and_measured_bytes(tiny_model):
    params, cfg = tiny_model
    qp, report = wp.quantize_params(params, cfg, FULL_POLICY)
    layers = qp["layers"]
    for key in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert wp.is_qtensor(layers[key]), key
    for key in ("attn_norm_w", "mlp_norm_w"):
        assert not wp.is_qtensor(layers[key]), key       # norms stay f32
    assert wp.is_qtensor(qp["embed"])
    assert wp.is_qtensor(qp["lm_head"])
    assert not wp.is_qtensor(qp["final_norm_w"])
    assert wp.is_quantized_tree(qp) and not wp.is_quantized_tree(params)
    # measured resident bytes: int8 + scale planes ~3-4x under f32
    ratio = wp.resident_weight_bytes(params) / \
        wp.resident_weight_bytes(qp)
    assert ratio >= 3.0, ratio
    assert report["leaves_quantized"] == 9
    desc = wp.describe_tree(qp)
    assert desc["dtype"] == "int8" and desc["int8_leaves"] == 9
    # default policy (no embed/head) keeps the gather + head f32
    qp2, _ = wp.quantize_params(
        params, cfg, wp.WeightPlaneConfig(tier="relaxed", group=16))
    assert not wp.is_qtensor(qp2["embed"])
    assert not wp.is_qtensor(qp2["lm_head"])
    assert wp.is_qtensor(qp2["layers"]["wq"])
    # a bitwise config reaching the quantizer is a wiring bug, not a
    # silent quantization — enforced by the module, not the call site
    with pytest.raises(ValueError, match="relaxed"):
        wp.quantize_params(params, cfg, wp.WeightPlaneConfig())


def test_tied_embeddings_flags_must_agree():
    cfg = get_config("tiny-gpt2")                    # tie_embeddings
    params = init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="tied"):
        wp.quantize_params(params, cfg, wp.WeightPlaneConfig(
            tier="relaxed", group=16, quant_head=True))
    # agreeing flags quantize the ONE matrix once, serving both faces
    qp, _ = wp.quantize_params(params, cfg, wp.WeightPlaneConfig(
        tier="relaxed", group=16, quant_embed=True, quant_head=True))
    assert wp.is_qtensor(qp["embed"])
    ab = wp.run_weight_ab(cfg, params, qp, min_agree=0.0, rel_tol=10.0)
    assert np.isfinite(ab["max_abs"])


# ------------------------------------------------- bitwise default tier

def test_bitwise_default_is_byte_identical_serving(tiny_model):
    """serving.parity unset -> bitwise: raw params, no quantized leaf,
    and the engine's greedy tokens still match the full-recompute
    reference exactly (the pre-weight-plane contract, untouched)."""
    params, cfg = tiny_model
    assert wp.weightplane_from_conf(None).tier == "bitwise"
    assert wp.weightplane_from_conf(
        Configuration(load_defaults=False)).tier == "bitwise"
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=64)
    assert not eng._relaxed_weights
    assert eng.weight_plane()["parity"] == "bitwise"
    assert eng.weight_plane()["dtype"] == "float32"
    prompt = [3, 1, 4, 1, 5]
    out = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
    # reference: argmax through models.decoder.forward, step by step
    seq = list(prompt)
    for _ in range(6):
        logits = forward(params, jnp.asarray([seq]), cfg)
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert out == seq[len(prompt):]


# ------------------------------------------------------ the relaxed tier

def test_quantized_engine_accepted_by_logits_guard(tiny_model):
    params, cfg = tiny_model
    qp, _ = wp.quantize_params(params, cfg, FULL_POLICY)
    report = wp.run_weight_ab(cfg, params, qp, wp=FULL_POLICY)
    assert report["accepted"], report
    assert report["greedy_agree"] >= 0.95
    # and the engine actually decodes through the int8 plane with the
    # compile-once contract intact
    eng = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                       max_context=64)
    assert eng._relaxed_weights
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size, size=9).tolist()
               for _ in range(4)]
    outs = eng.generate(prompts, SamplingParams(max_new_tokens=6))
    assert all(len(o) == 6 for o in outs)
    assert eng.decode_compiles == 1 and eng.prefill_compiles == 1
    # deterministic: the same quantized plane replays the same tokens
    eng2 = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                        max_context=64)
    assert eng2.generate(prompts,
                         SamplingParams(max_new_tokens=6)) == outs


def test_guard_rejects_a_broken_weight_plane(tiny_model):
    """The guard must be falsifiable: zeroing a quantized layer's
    payload re-ranks the logits and the A-B rejects."""
    params, cfg = tiny_model
    qp, _ = wp.quantize_params(params, cfg, FULL_POLICY)
    broken = jax.tree_util.tree_map(lambda x: x, qp)   # deep-ish copy
    broken["layers"] = dict(qp["layers"])
    wo = qp["layers"]["wo"]
    broken["layers"]["wo"] = {"q": jnp.zeros_like(wo["q"]),
                              "s": wo["s"]}
    report = wp.run_weight_ab(cfg, params, broken, wp=FULL_POLICY)
    assert not report["accepted"]


def test_hbm_budget_converts_weight_bytes_into_lanes(tiny_model):
    """One fixed HBM budget, two planes: the engine sizes KV blocks and
    decode lanes against the MEASURED resident-weight bytes, so the
    int8 plane admits >= 2x the lanes x context."""
    params, cfg = tiny_model
    qp, _ = wp.quantize_params(params, cfg, FULL_POLICY)
    bs, mc = 4, 64
    bnb = 2 * cfg.n_layers * bs * cfg.n_kv_heads * cfg.head_dim * 4
    budget = wp.resident_weight_bytes(params) + \
        (2 * (mc // bs) + 2) * bnb
    e32 = DecodeEngine(params, cfg, block_size=bs, max_context=mc,
                       hbm_bytes=budget)
    e8 = DecodeEngine(qp, cfg, block_size=bs, max_context=mc,
                      hbm_bytes=budget)
    assert e32.max_batch == 2
    assert e8.max_batch >= 2 * e32.max_batch
    cap32 = e32.weight_plane()["lanes_x_context"]
    cap8 = e8.weight_plane()["lanes_x_context"]
    assert cap8 >= 2 * cap32, (cap8, cap32)
    assert e8.pool.num_usable >= 2 * e32.pool.num_usable
    # a budget the weights alone overflow is a loud error
    with pytest.raises(ValueError, match="hbm"):
        DecodeEngine(params, cfg, block_size=bs, max_context=mc,
                     hbm_bytes=wp.resident_weight_bytes(params) + bnb)


def test_quantize_at_load_streams_per_shard(tmp_path, tiny_model):
    """Quantize-at-load: the loader's per-leaf streaming keeps peak
    host f32 bytes bounded below the full model, and the streamed tree
    is BIT-identical to the in-memory policy application (one policy,
    two paths, zero drift)."""
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    params, cfg = tiny_model
    fs = LocalFileSystem()
    save_checkpoint(fs, f"{tmp_path}/ckpt", 5,
                    {"params": params, "opt": {}})
    qp_mem, _ = wp.quantize_params(params, cfg, FULL_POLICY)
    qp_load, step, report = wp.quantized_load(
        fs, f"{tmp_path}/ckpt", cfg, FULL_POLICY, io_workers=4)
    assert step == 5
    assert 0 < report["peak_f32_bytes"] < report["total_f32_bytes"]
    assert report["weight_bytes"] == wp.resident_weight_bytes(qp_mem)
    assert report["quantize_seconds"] >= 0.0
    a = jax.tree_util.tree_leaves(qp_mem)
    b = jax.tree_util.tree_leaves(qp_load)
    assert len(a) == len(b)
    assert all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(a, b))
    # and the streamed tree serves
    eng = DecodeEngine(qp_load, cfg, max_batch=2, block_size=4,
                       max_context=64)
    assert len(eng.generate([[1, 2, 3]],
                            SamplingParams(max_new_tokens=3))[0]) == 3


# ------------------------------------------------- observability surface

def test_weight_plane_rides_health_and_prom(tiny_model):
    """/v1/health reports the weight plane next to the cache stats and
    the htpu_weight_bytes gauge lands on /prom (same test as the
    traffic: the metrics system resets between tests)."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    from hadoop_tpu.serving.metrics import ServingMetrics
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    qp, rep = wp.quantize_params(params, cfg, FULL_POLICY)
    eng = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                       max_context=64, metrics=ServingMetrics(),
                       quantize_seconds=rep["quantize_seconds"])
    server = ServingServer(eng, Configuration(load_defaults=False))
    status, health = server._health({}, b"")
    assert status == 200
    weights = health["weights"]
    assert weights["parity"] == "relaxed"
    assert weights["dtype"] == "int8"
    assert weights["weight_bytes"] == wp.resident_weight_bytes(qp)
    assert weights["quantize_seconds"] == rep["quantize_seconds"]
    assert weights["lanes_x_context"] == eng.max_batch * eng.s_max
    prom = render_prom(metrics_system())
    line = [ln for ln in prom.splitlines()
            if ln.startswith("htpu_weight_bytes")]
    assert line and float(line[0].rsplit(" ", 1)[1]) == \
        wp.resident_weight_bytes(qp)


def test_replica_lifecycle_relaxed_parity(tmp_path, tiny_model):
    """ServingReplica end-to-end under serving.parity=relaxed: the
    checkpoint streams through the quantizer at load, the registry
    record and /v1/health report the int8 weight plane, and the door
    serves greedy tokens."""
    import http.client
    import json as _json

    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.registry import RegistryServer
    from hadoop_tpu.serving.service import ServingReplica
    params, cfg = tiny_model
    save_checkpoint(LocalFileSystem(), f"{tmp_path}/ckpt", 2,
                    {"params": params, "opt": {}})
    conf = Configuration(load_defaults=False)
    conf.set("serving.parity", "relaxed")
    conf.set("serving.weights.group", "16")
    conf.set("serving.weights.embed", "true")
    conf.set("serving.weights.head", "true")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    try:
        replica = ServingReplica(
            conf, name="wplane", checkpoint=f"file://{tmp_path}/ckpt",
            preset="tiny", registry_addr=("127.0.0.1", reg_srv.port),
            instance="i0")
        replica.start()
        rec = reg_srv.list("/services/serving/wplane")[0]
        assert rec.attributes["weight_dtype"] == "int8"
        assert int(rec.attributes["weight_bytes"]) == \
            replica.engine.weight_bytes
        assert float(rec.attributes["quantize_seconds"]) >= 0.0
        conn = http.client.HTTPConnection("127.0.0.1",
                                          replica.server.port, timeout=30)
        conn.request("GET", "/v1/health")
        health = _json.loads(conn.getresponse().read())
        assert health["weights"]["dtype"] == "int8"
        conn.request("POST", "/v1/generate", body=_json.dumps(
            {"tokens": [1, 2, 3], "max_new_tokens": 4}).encode())
        resp = conn.getresponse()
        body = _json.loads(resp.read())
        assert resp.status == 200 and len(body["tokens"]) == 4
        conn.close()
        replica.drain_and_stop(timeout=15)
    finally:
        reg_srv.stop()
