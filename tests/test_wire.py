"""Wire format round-trip tests."""

import io

import pytest

from hadoop_tpu.io.wire import (WireError, pack, read_frame, unpack,
                                unpack_with_offset, write_frame)


@pytest.mark.parametrize("value", [
    None, True, False, 0, 1, 127, 128, -1, -32, -33, 2**40, -(2**40),
    2**70, -(2**70), 0.0, -1.5, 3.14159, "", "hi", "x" * 31, "x" * 32,
    "日本語テキスト", b"", b"\x00\xff" * 100, [], [1, 2, 3], list(range(50)),
    {}, {"a": 1}, {"k" + str(i): i for i in range(40)},
    {"nested": {"list": [1, "two", b"three", None, {"deep": [[[]]]}]}},
])
def test_roundtrip(value):
    assert unpack(pack(value)) == value


def test_tuple_decodes_as_list():
    assert unpack(pack((1, 2))) == [1, 2]


def test_small_values_compact():
    assert len(pack(5)) == 1
    assert len(pack("abc")) == 4
    assert len(pack({})) == 1
    assert len(pack([1, 2, 3])) == 4


def test_non_str_key_rejected():
    with pytest.raises(WireError):
        pack({1: "x"})


def test_unencodable_rejected():
    with pytest.raises(WireError):
        pack(object())


def test_truncated_raises():
    data = pack({"k": "value-that-is-long-enough"})
    with pytest.raises(WireError):
        unpack(data[:-3])


def test_offset_chaining():
    data = pack(1) + pack("two") + pack([3])
    v1, off = unpack_with_offset(data, 0)
    v2, off = unpack_with_offset(data, off)
    v3, off = unpack_with_offset(data, off)
    assert (v1, v2, v3) == (1, "two", [3])
    assert off == len(data)


def test_to_wire_objects():
    class Point:
        def to_wire(self):
            return {"x": 1, "y": 2}
    assert unpack(pack(Point())) == {"x": 1, "y": 2}
    assert unpack(pack([Point(), Point()])) == [{"x": 1, "y": 2}] * 2


def test_stream_framing():
    buf = io.BytesIO()
    write_frame(buf, pack({"msg": "hello"}))
    write_frame(buf, pack([1, 2]))
    buf.seek(0)
    assert unpack(read_frame(buf)) == {"msg": "hello"}
    assert unpack(read_frame(buf)) == [1, 2]


def test_frame_limit():
    buf = io.BytesIO()
    write_frame(buf, b"x" * 100)
    buf.seek(0)
    with pytest.raises(WireError):
        read_frame(buf, max_frame=10)


def test_c_codec_byte_identical_to_python():
    """The wirepack C accelerator (native/src/wirepack.c) must be
    byte-identical to the Python codec on encode AND agree on decode —
    the Python implementation is the format's executable spec."""
    import random
    import string

    from hadoop_tpu.io import wire
    if wire._C is None:
        import pytest
        pytest.skip("C codec not built")
    rng = random.Random(7)

    def tree(depth=0):
        kinds = ["int", "str", "bytes", "float", "none", "bool", "list",
                 "dict"]
        k = rng.choice(kinds if depth < 4 else kinds[:6])
        if k == "int":
            return rng.choice([0, 1, 127, 128, -1, -32, -33, 2**40,
                               -(2**40), 2**62 - 1, -(2**62)])
        if k == "str":
            return "".join(rng.choices(string.printable,
                                       k=rng.randrange(0, 40)))
        if k == "bytes":
            return bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 50)))
        if k == "float":
            return rng.random() * 1e6
        if k == "none":
            return None
        if k == "bool":
            return rng.random() < 0.5
        if k == "list":
            return [tree(depth + 1) for _ in range(rng.randrange(0, 20))]
        return {f"k{i}": tree(depth + 1)
                for i in range(rng.randrange(0, 20))}

    for _ in range(500):
        t = tree()
        py = wire.Encoder().encode(t).getvalue()
        assert py == wire._C.pack(t)
        assert wire._C.unpack(py) == wire.Decoder(py).decode() == t


def test_c_codec_bigint_and_object_fallback():
    from hadoop_tpu.io import wire

    # >64-bit ints round-trip through the Python fallback transparently
    big = {"x": 2**80, "y": [-(2**77)]}
    assert wire.unpack(wire.pack(big)) == big

    class Rec:
        def to_wire(self):
            return {"a": 1}

    assert wire.unpack(wire.pack({"r": Rec()})) == {"r": {"a": 1}}
    # error classes match across codecs
    import pytest
    with pytest.raises(wire.WireError):
        wire.unpack(b"\xca")
    with pytest.raises(wire.WireError):
        wire.pack({1: "non-str key"})


def test_c_decoder_hostile_lengths_and_offsets():
    """Hostile framing must fail as WireError, never escape as
    SystemError/OOB (review findings: signed-overflow length checks,
    negative offsets)."""
    import struct

    import pytest as _p

    from hadoop_tpu.io.wire import WireError, pack, unpack

    # bin frame claiming a 2^62-byte payload
    evil = b"\xc4" + b"\xff\xff\xff\xff\xff\xff\xff\xff\x3f"
    with _p.raises((WireError, OverflowError)):
        unpack(evil)
    # str frame with a >=2^63 length (negative after a signed cast)
    evil2 = b"\xc5" + b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"
    with _p.raises((WireError, OverflowError)):
        unpack(evil2)
    # negative offset must not read before the buffer
    good = pack({"k": 1})
    with _p.raises((WireError, OverflowError, ValueError)):
        unpack(good, -16)


def test_native_merge_rejects_hostile_segments():
    """Crafted shuffle segments (valid CRC, hostile framing) must fail
    the native k-way merge cleanly — not read past the heap (review
    findings: uint32 klen+vlen wraparound; unbounded varints)."""
    import struct

    from hadoop_tpu import native as nat

    if not nat.available():
        import pytest as _pt
        _pt.skip("native library unavailable")

    def seg(body: bytes) -> bytes:
        return body + struct.pack(">I", nat.crc32c(0, body))

    import pytest as _pt

    # varint klen 0xFFFFFFF0 + vlen 0x20 -> uint32 wrap passes p<=end
    wrap = b"\xf0\xff\xff\xff\x0f" + b"\x20" + b"k" * 8 + \
        b"\xff\xff\xff\xff"
    with _pt.raises(IOError):
        nat.merge_segments([seg(wrap)], raw=False)

    # a valid record then trailing 0x80 continuation bytes (no EOF
    # marker): the varint reader must stop at the segment end
    cont = b"\x01\x01kv" + b"\x80\x80\x80"
    with _pt.raises(IOError):
        nat.merge_segments([seg(cont)], raw=False)
