"""YARN federation: router over two live subclusters.

Mirrors the reference's router tests (ref: hadoop-yarn-server-router
TestFederationClientInterceptor.java — submit/report/kill through the
router against federated RMs; policy tests ref:
TestLoadBasedRouterPolicy).
"""

import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.examples.distributed_shell import submit
from hadoop_tpu.testing.minicluster import MiniYARNCluster
from hadoop_tpu.yarn.client import YarnClient
from hadoop_tpu.yarn.federation import SC_LOST, YarnRouter
from hadoop_tpu.yarn.records import AppState


@pytest.fixture(scope="module")
def federation(tmp_path_factory):
    base = tmp_path_factory.mktemp("fed")
    with MiniYARNCluster(num_nodes=1) as c1, \
            MiniYARNCluster(num_nodes=1) as c2:
        conf = Configuration(other=c1.conf)
        conf.set("yarn.federation.subcluster.sc1",
                 f"{c1.rm_addr[0]}:{c1.rm_addr[1]}")
        conf.set("yarn.federation.subcluster.sc2",
                 f"{c2.rm_addr[0]}:{c2.rm_addr[1]}")
        conf.set("yarn.federation.policy", "round-robin")
        router = YarnRouter(conf, state_dir=str(base))
        router.init(conf)
        router.start()
        try:
            yield c1, c2, router
        finally:
            router.stop()


def test_router_aggregates_cluster_state(federation):
    c1, c2, router = federation
    yc = YarnClient(("127.0.0.1", router.port),
                    Configuration(other=c1.conf))
    try:
        metrics = yc.cluster_metrics()
        assert metrics["num_node_managers"] == 2
        assert metrics["subclusters"] == 2
        nodes = yc.nodes()
        assert {n["subcluster"] for n in nodes} == {"sc1", "sc2"}
    finally:
        yc.close()


def test_router_routes_apps_round_robin(federation):
    c1, c2, router = federation
    router_addr = ("127.0.0.1", router.port)
    yc = YarnClient(router_addr, Configuration(other=c1.conf))
    try:
        app_ids = []
        for _ in range(2):
            app_id = submit(router_addr, ["bash", "-c", "true"], n=1,
                            conf=Configuration(other=c1.conf))
            app_ids.append(app_id)
        for app_id in app_ids:
            report = yc.wait_for_completion(app_id, timeout=60)
            assert report.state == AppState.FINISHED, report.diagnostics
        # Round-robin put one app on each subcluster.
        homes = {router.store.home_of(str(a)) for a in app_ids}
        assert homes == {"sc1", "sc2"}
        # Aggregated listing sees both.
        listed = {str(r.app_id) for r in yc.list_applications()}
        assert {str(a) for a in app_ids} <= listed
    finally:
        yc.close()


def test_router_marks_lost_subcluster(federation):
    c1, c2, router = federation
    # Point sc2's registration at a dead port and wait for the liveness
    # sweep to mark it LOST; routing then avoids it.
    router.store.register_subcluster("sc-dead", "127.0.0.1:1")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        sc = router.store.subclusters().get("sc-dead")
        if sc and sc["state"] == SC_LOST:
            break
        time.sleep(0.3)
    assert router.store.subclusters()["sc-dead"]["state"] == SC_LOST
    for _ in range(4):
        assert router.choose_subcluster() != "sc-dead"
    assert router.store.deregister_subcluster("sc-dead")


def test_policy_store_weighted_and_reject(federation):
    """Per-queue policies from the policy store drive placement (ref:
    WeightedRandomRouterPolicy + RejectRouterPolicy resolved per
    queue)."""
    c1, c2, router = federation
    from hadoop_tpu.ipc import get_proxy
    admin = get_proxy("RouterAdminProtocol", ("127.0.0.1", router.port))
    # queue 'prod' pinned to sc1 by weights; queue 'closed' rejects
    assert admin.set_policy("prod", {"type": "weighted",
                                     "weights": {"sc1": 1.0}})
    assert admin.set_policy("closed", {"type": "reject"})
    assert admin.get_policy("prod")["type"] == "weighted"
    for _ in range(3):
        assert router.choose_subcluster("prod") == "sc1"
    with pytest.raises(IOError, match="reject"):
        router.choose_subcluster("closed")
    # a bogus policy config is refused at set time
    with pytest.raises(Exception):
        admin.set_policy("broken", {"type": "weighted", "weights": "x"})


def test_interceptor_chain_audits_calls(federation):
    c1, c2, router = federation
    from hadoop_tpu.ipc import get_proxy
    from hadoop_tpu.yarn.federation import (FederationClientInterceptor,
                                            RouterAuditInterceptor)
    # chain shape: audit → federation (terminal)
    assert isinstance(router.chain, RouterAuditInterceptor)
    assert isinstance(router.chain.next, FederationClientInterceptor)
    yc = YarnClient(("127.0.0.1", router.port),
                    Configuration(other=c1.conf))
    try:
        yc.cluster_metrics()
        yc.cluster_metrics()
    finally:
        yc.close()
    admin = get_proxy("RouterAdminProtocol", ("127.0.0.1", router.port))
    counts = admin.interceptor_counts()
    assert counts.get("get_cluster_metrics", 0) >= 2


def test_apps_survive_subcluster_rm_death(tmp_path):
    """The VERDICT scenario: two subclusters under one router; one
    subcluster's RM dies with apps running. Apps homed on the survivor
    finish; new submissions route around the corpse; after the dead RM
    restarts (work-preserving recovery), its app completes too."""
    import os as _os

    base = str(tmp_path)
    with MiniYARNCluster(num_nodes=1) as c1, \
            MiniYARNCluster(num_nodes=1) as c2:
        conf = Configuration(other=c1.conf)
        conf.set("yarn.federation.subcluster.sc1",
                 f"{c1.rm_addr[0]}:{c1.rm_addr[1]}")
        conf.set("yarn.federation.subcluster.sc2",
                 f"{c2.rm_addr[0]}:{c2.rm_addr[1]}")
        conf.set("yarn.federation.policy", "round-robin")
        conf.set("yarn.federation.liveness-interval", "0.5s")
        router = YarnRouter(conf, state_dir=base)
        router.init(conf)
        router.start()
        try:
            router_addr = ("127.0.0.1", router.port)
            cconf = Configuration(other=c1.conf)
            # two long-enough apps, one per subcluster (round-robin)
            a1 = submit(router_addr, ["bash", "-c", "sleep 2"], n=1,
                        conf=cconf)
            a2 = submit(router_addr, ["bash", "-c", "sleep 2"], n=1,
                        conf=cconf)
            homes = {str(a1): router.store.home_of(str(a1)),
                     str(a2): router.store.home_of(str(a2))}
            assert set(homes.values()) == {"sc1", "sc2"}
            dead_sc = "sc1"
            survivor_app = next(a for a in (a1, a2)
                                if homes[str(a)] != dead_sc)
            victim_app = next(a for a in (a1, a2)
                              if homes[str(a)] == dead_sc)

            c1.rm.stop()  # kill one subcluster's RM mid-flight

            yc = YarnClient(router_addr, cconf)
            try:
                # survivor's app completes through the router
                report = yc.wait_for_completion(survivor_app, timeout=60)
                assert report.state == AppState.FINISHED, report.diagnostics
                # new submissions keep working and avoid the dead
                # subcluster (eager LOST marking / liveness sweep)
                a3 = submit(router_addr, ["bash", "-c", "true"], n=1,
                            conf=cconf)
                assert router.store.home_of(str(a3)) != dead_sc
                report = yc.wait_for_completion(a3, timeout=60)
                assert report.state == AppState.FINISHED, report.diagnostics
                # aggregate reads keep answering with the survivor
                assert yc.cluster_metrics()["subclusters"] == 1

                # the dead RM comes back with its state: recovery resumes
                # the victim's app and the router serves it again
                c1.restart_rm()
                c1.wait_nodes()
                report = yc.wait_for_completion(victim_app, timeout=60)
                assert report.state == AppState.FINISHED, report.diagnostics
            finally:
                yc.close()
        finally:
            router.stop()


def test_queue_policy_enforced_on_real_submissions(federation):
    """The per-queue policy must bind on the REAL client path (review
    finding: it used to be consulted only for queue 'default'): a
    weighted policy pins a queue's apps to one subcluster, and a
    reject policy refuses the submission itself."""
    from hadoop_tpu.ipc import get_proxy
    from hadoop_tpu.yarn.records import (ApplicationSubmissionContext,
                                         ContainerLaunchContext, Resource)

    c1, c2, router = federation
    admin = get_proxy("RouterAdminProtocol", ("127.0.0.1", router.port))
    admin.set_policy("pinned", {"type": "weighted",
                                "weights": {"sc2": 1.0}})
    admin.set_policy("closed", {"type": "reject"})

    yc = YarnClient(("127.0.0.1", router.port),
                    Configuration(other=c1.conf))
    try:
        for _ in range(2):
            app_id, _ = yc.create_application()
            ctx = ApplicationSubmissionContext(
                app_id, "pinned-app",
                ContainerLaunchContext(["bash", "-c", "true"], {}, {}),
                Resource(64, 1), queue="pinned", unmanaged=True)
            yc.submit_application(ctx, wait_accepted=False)
            assert router.store.home_of(str(app_id)) == "sc2"

        app_id, _ = yc.create_application()
        ctx = ApplicationSubmissionContext(
            app_id, "rejected-app",
            ContainerLaunchContext(["bash", "-c", "true"], {}, {}),
            Resource(64, 1), queue="closed", unmanaged=True)
        with pytest.raises(Exception, match="reject|no subcluster"):
            yc.submit_application(ctx, wait_accepted=False)
        assert router.store.home_of(str(app_id)) is None
    finally:
        yc.close()


def test_mark_lost_does_not_resurrect_deregistered(federation):
    """An administratively deregistered subcluster stays deregistered
    even when a stale caller hits a transient error against it —
    mark_lost demoting it to LOST would put it back on the liveness
    sweep's probe list and resurrect a drained-but-running RM into
    routing (review finding)."""
    from hadoop_tpu.yarn.federation import SC_DEREGISTERED
    c1, c2, router = federation
    # register a live-but-drained subcluster, then deregister it
    router.store.register_subcluster(
        "sc-drained", f"{c1.rm_addr[0]}:{c1.rm_addr[1]}")
    assert router.store.deregister_subcluster("sc-drained")
    router.mark_lost("sc-drained")
    assert router.store.subclusters()["sc-drained"]["state"] == \
        SC_DEREGISTERED
    # two liveness sweeps later it still must not be probed back ACTIVE
    time.sleep(2.5)
    assert router.store.subclusters()["sc-drained"]["state"] == \
        SC_DEREGISTERED
    router.store._subclusters.pop("sc-drained", None)  # cleanup


def test_set_policy_rejects_unknown_type(federation):
    """A typo'd policy type fails set_policy loudly instead of silently
    routing by the load-based default forever (review finding)."""
    c1, c2, router = federation
    from hadoop_tpu.ipc import get_proxy
    admin = get_proxy("RouterAdminProtocol", ("127.0.0.1", router.port))
    with pytest.raises(Exception, match="unknown router policy"):
        admin.set_policy("typo-queue", {"type": "round_robin"})
    assert router.store.policy_for("typo-queue") is None
