"""YARN federation: router over two live subclusters.

Mirrors the reference's router tests (ref: hadoop-yarn-server-router
TestFederationClientInterceptor.java — submit/report/kill through the
router against federated RMs; policy tests ref:
TestLoadBasedRouterPolicy).
"""

import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.examples.distributed_shell import submit
from hadoop_tpu.testing.minicluster import MiniYARNCluster
from hadoop_tpu.yarn.client import YarnClient
from hadoop_tpu.yarn.federation import SC_LOST, YarnRouter
from hadoop_tpu.yarn.records import AppState


@pytest.fixture(scope="module")
def federation(tmp_path_factory):
    base = tmp_path_factory.mktemp("fed")
    with MiniYARNCluster(num_nodes=1) as c1, \
            MiniYARNCluster(num_nodes=1) as c2:
        conf = Configuration(other=c1.conf)
        conf.set("yarn.federation.subcluster.sc1",
                 f"{c1.rm_addr[0]}:{c1.rm_addr[1]}")
        conf.set("yarn.federation.subcluster.sc2",
                 f"{c2.rm_addr[0]}:{c2.rm_addr[1]}")
        conf.set("yarn.federation.policy", "round-robin")
        router = YarnRouter(conf, state_dir=str(base))
        router.init(conf)
        router.start()
        try:
            yield c1, c2, router
        finally:
            router.stop()


def test_router_aggregates_cluster_state(federation):
    c1, c2, router = federation
    yc = YarnClient(("127.0.0.1", router.port),
                    Configuration(other=c1.conf))
    try:
        metrics = yc.cluster_metrics()
        assert metrics["num_node_managers"] == 2
        assert metrics["subclusters"] == 2
        nodes = yc.nodes()
        assert {n["subcluster"] for n in nodes} == {"sc1", "sc2"}
    finally:
        yc.close()


def test_router_routes_apps_round_robin(federation):
    c1, c2, router = federation
    router_addr = ("127.0.0.1", router.port)
    yc = YarnClient(router_addr, Configuration(other=c1.conf))
    try:
        app_ids = []
        for _ in range(2):
            app_id = submit(router_addr, ["bash", "-c", "true"], n=1,
                            conf=Configuration(other=c1.conf))
            app_ids.append(app_id)
        for app_id in app_ids:
            report = yc.wait_for_completion(app_id, timeout=60)
            assert report.state == AppState.FINISHED, report.diagnostics
        # Round-robin put one app on each subcluster.
        homes = {router.store.home_of(str(a)) for a in app_ids}
        assert homes == {"sc1", "sc2"}
        # Aggregated listing sees both.
        listed = {str(r.app_id) for r in yc.list_applications()}
        assert {str(a) for a in app_ids} <= listed
    finally:
        yc.close()


def test_router_marks_lost_subcluster(federation):
    c1, c2, router = federation
    # Point sc2's registration at a dead port and wait for the liveness
    # sweep to mark it LOST; routing then avoids it.
    router.store.register_subcluster("sc-dead", "127.0.0.1:1")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        sc = router.store.subclusters().get("sc-dead")
        if sc and sc["state"] == SC_LOST:
            break
        time.sleep(0.3)
    assert router.store.subclusters()["sc-dead"]["state"] == SC_LOST
    for _ in range(4):
        assert router.choose_subcluster() != "sc-dead"
    assert router.store.deregister_subcluster("sc-dead")
