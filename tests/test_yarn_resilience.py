"""Fair scheduler, preemption, and work-preserving RM restart.

Ref targets: scheduler/fair/FairScheduler.java, monitor/capacity/
ProportionalCapacityPreemptionPolicy.java, recovery/ZKRMStateStore.java:180
(+ TestWorkPreservingRMRestart's bounce-the-RM-keep-the-store pattern).
"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.records import (ApplicationId, ContainerId, NodeId,
                                     Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import (CapacityScheduler, FairScheduler,
                                       make_scheduler)


def _cid_factory():
    app = ApplicationId(1, 1)
    seqs = {}

    def make(attempt_id, seq):
        no = int(attempt_id.rsplit("_", 1)[1])
        return ContainerId(app, no, seqs.setdefault(attempt_id, 0) + seq)
    return make


def _drive(sched, node_id):
    sched.node_heartbeat(node_id)


def test_fair_scheduler_shares_by_weight():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.fair.queues", "gold,silver")
    conf.set("yarn.scheduler.fair.root.gold.weight", "3.0")
    conf.set("yarn.scheduler.fair.root.silver.weight", "1.0")
    s = FairScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(8000, 8), "h1:1")
    s.add_app("application_1_1_01", "gold", "u")
    s.add_app("application_1_2_01", "silver", "u")
    # both ask for everything; fair share should land ~3:1 by memory
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    for _ in range(8):
        _drive(s, nid)
    gold, _ = s.allocate("application_1_1_01", [], [])
    silver, _ = s.allocate("application_1_2_01", [], [])
    assert len(gold) + len(silver) == 8
    assert len(gold) == 6 and len(silver) == 2  # 3:1 split of 8 containers


def test_fair_scheduler_auto_creates_queue():
    conf = Configuration(load_defaults=False)
    s = FairScheduler(conf, _cid_factory())
    s.add_app("application_1_1_01", "adhoc", "u")  # no error
    assert "adhoc" in s.weights


def test_make_scheduler_kinds():
    for kind, cls in (("fair", "FairScheduler"),
                      ("capacity", "CapacityScheduler"),
                      ("fifo", "FifoScheduler")):
        conf = Configuration(load_defaults=False)
        conf.set("yarn.resourcemanager.scheduler.class", kind)
        assert type(make_scheduler(conf, _cid_factory())).__name__ == cls


def test_capacity_preemption_candidates():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", "a,b")
    conf.set("yarn.scheduler.capacity.root.a.capacity", "50")
    conf.set("yarn.scheduler.capacity.root.b.capacity", "50")
    s = CapacityScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(8000, 8), "h1:1")
    # app A (queue a) grabs the whole cluster
    s.add_app("application_1_1_01", "a", "u")
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    for _ in range(8):
        _drive(s, nid)
    got_a, _ = s.allocate("application_1_1_01", [], [])
    assert len(got_a) == 8
    # no starvation yet → nothing to preempt
    assert s.preemption_candidates() == []
    # app B (queue b) arrives with demand it can't place
    s.add_app("application_1_2_01", "b", "u")
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 4, Resource(1000, 1))], [])
    victims = s.preemption_candidates()
    assert victims, "over-capacity queue must yield victims"
    assert all(aid == "application_1_1_01" for aid, _ in victims)
    # protected (AM) containers are skipped
    protected = {str(got_a[0].container_id)}
    victims2 = s.preemption_candidates(
        protect=lambda cid: str(cid) in protected)
    assert all(str(c.container_id) not in protected for _, c in victims2)


def test_fair_preemption_candidates():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.fair.queues", "a,b")
    s = FairScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(4000, 4), "h1:1")
    s.add_app("application_1_1_01", "a", "u")
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 4, Resource(1000, 1))], [])
    for _ in range(4):
        _drive(s, nid)
    s.add_app("application_1_2_01", "b", "u")
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 2, Resource(1000, 1))], [])
    assert s.preemption_candidates()


# ------------------------------------------------- work-preserving restart


def test_work_preserving_rm_restart(tmp_path):
    """Bounce the RM mid-job: NMs re-register with live containers, the
    AM re-registers and re-asks, running work is NOT restarted, and the
    job completes. Ref: TestWorkPreservingRMRestart."""
    from hadoop_tpu.examples.wordcount import TokenizerMapper
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    from hadoop_tpu.mapreduce import history
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.testing.mr_helpers import SlowGateReducer

    with MiniMRYarnCluster(num_nodes=2) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/wp-in")
        for i in range(2):
            fs.write_all(f"/wp-in/f{i}.txt",
                         (f"one two three {i}\n" * 40).encode())
        gate = str(tmp_path / "gate")
        open(gate, "w").close()
        job = (Job(cluster.rm_addr, cluster.default_fs, name="wp")
               .set_mapper(TokenizerMapper)
               .set_reducer(class_ref(SlowGateReducer))
               .add_input_path("/wp-in")
               .set_output_path("/wp-out")
               .set_num_reduces(1)
               .set("test.reduce.gate", gate))
        job.submit()

        # wait until the maps are done (the job is mid-flight: reduce
        # gated) so the restart happens with live AM + reduce containers
        hist = f"/tmp/staging/{job.job_id}/history"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            done = [e for e in history.read_events(fs, hist)
                    if e["type"] == history.TASK_FINISHED]
            if len(done) >= 2:
                break
            time.sleep(0.2)
        assert len(done) >= 2, "maps never finished"

        cluster.yarn.restart_rm()
        time.sleep(1.0)
        os.remove(gate)

        assert job.wait_for_completion(timeout=90), job.diagnostics
        # work-preserving: the AM was NOT restarted — the RM knows only
        # attempt 1 and the history has each map exactly once
        evs = list(history.read_events(
            fs, f"/mr-history/done/{job.job_id}"))
        maps = [e["task_id"] for e in evs
                if e["type"] == history.TASK_FINISHED
                and e["task_type"] == "map"]
        assert len(maps) == len(set(maps)) == 2
        report = cluster.yarn.rm.apps[job._app_id].report()
        assert report.attempt_no == 1, "AM must not have been relaunched"
