"""Fair scheduler, preemption, and work-preserving RM restart.

Ref targets: scheduler/fair/FairScheduler.java, monitor/capacity/
ProportionalCapacityPreemptionPolicy.java, recovery/ZKRMStateStore.java:180
(+ TestWorkPreservingRMRestart's bounce-the-RM-keep-the-store pattern).
"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.records import (ApplicationId, ContainerId, NodeId,
                                     Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import (CapacityScheduler, FairScheduler,
                                       make_scheduler)


def _cid_factory():
    app = ApplicationId(1, 1)
    seqs = {}

    def make(attempt_id, seq):
        no = int(attempt_id.rsplit("_", 1)[1])
        return ContainerId(app, no, seqs.setdefault(attempt_id, 0) + seq)
    return make


def _drive(sched, node_id):
    sched.node_heartbeat(node_id)


def test_fair_scheduler_shares_by_weight():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.fair.queues", "gold,silver")
    conf.set("yarn.scheduler.fair.root.gold.weight", "3.0")
    conf.set("yarn.scheduler.fair.root.silver.weight", "1.0")
    s = FairScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(8000, 8), "h1:1")
    s.add_app("application_1_1_01", "gold", "u")
    s.add_app("application_1_2_01", "silver", "u")
    # both ask for everything; fair share should land ~3:1 by memory
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    for _ in range(8):
        _drive(s, nid)
    gold, _ = s.allocate("application_1_1_01", [], [])
    silver, _ = s.allocate("application_1_2_01", [], [])
    assert len(gold) + len(silver) == 8
    assert len(gold) == 6 and len(silver) == 2  # 3:1 split of 8 containers


def test_fair_scheduler_auto_creates_queue():
    conf = Configuration(load_defaults=False)
    s = FairScheduler(conf, _cid_factory())
    s.add_app("application_1_1_01", "adhoc", "u")  # no error
    assert "adhoc" in s.weights


def test_make_scheduler_kinds():
    for kind, cls in (("fair", "FairScheduler"),
                      ("capacity", "CapacityScheduler"),
                      ("fifo", "FifoScheduler")):
        conf = Configuration(load_defaults=False)
        conf.set("yarn.resourcemanager.scheduler.class", kind)
        assert type(make_scheduler(conf, _cid_factory())).__name__ == cls


def test_capacity_preemption_candidates():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", "a,b")
    conf.set("yarn.scheduler.capacity.root.a.capacity", "50")
    conf.set("yarn.scheduler.capacity.root.b.capacity", "50")
    s = CapacityScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(8000, 8), "h1:1")
    # app A (queue a) grabs the whole cluster
    s.add_app("application_1_1_01", "a", "u")
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 8, Resource(1000, 1))], [])
    for _ in range(8):
        _drive(s, nid)
    got_a, _ = s.allocate("application_1_1_01", [], [])
    assert len(got_a) == 8
    # no starvation yet → nothing to preempt
    assert s.preemption_candidates() == []
    # app B (queue b) arrives with demand it can't place
    s.add_app("application_1_2_01", "b", "u")
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 4, Resource(1000, 1))], [])
    victims = s.preemption_candidates()
    assert victims, "over-capacity queue must yield victims"
    assert all(aid == "application_1_1_01" for aid, _ in victims)
    # protected (AM) containers are skipped
    protected = {str(got_a[0].container_id)}
    victims2 = s.preemption_candidates(
        protect=lambda cid: str(cid) in protected)
    assert all(str(c.container_id) not in protected for _, c in victims2)


def test_fair_preemption_candidates():
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.fair.queues", "a,b")
    s = FairScheduler(conf, _cid_factory())
    nid = NodeId("h1", 1)
    s.add_node(nid, Resource(4000, 4), "h1:1")
    s.add_app("application_1_1_01", "a", "u")
    s.allocate("application_1_1_01",
               [ResourceRequest(1, 4, Resource(1000, 1))], [])
    for _ in range(4):
        _drive(s, nid)
    s.add_app("application_1_2_01", "b", "u")
    s.allocate("application_1_2_01",
               [ResourceRequest(1, 2, Resource(1000, 1))], [])
    assert s.preemption_candidates()


# ------------------------------------------------- work-preserving restart


def test_work_preserving_rm_restart(tmp_path):
    """Bounce the RM mid-job: NMs re-register with live containers, the
    AM re-registers and re-asks, running work is NOT restarted, and the
    job completes. Ref: TestWorkPreservingRMRestart."""
    from hadoop_tpu.examples.wordcount import TokenizerMapper
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    from hadoop_tpu.mapreduce import history
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.testing.mr_helpers import SlowGateReducer

    with MiniMRYarnCluster(num_nodes=2) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/wp-in")
        for i in range(2):
            fs.write_all(f"/wp-in/f{i}.txt",
                         (f"one two three {i}\n" * 40).encode())
        gate = str(tmp_path / "gate")
        open(gate, "w").close()
        job = (Job(cluster.rm_addr, cluster.default_fs, name="wp")
               .set_mapper(TokenizerMapper)
               .set_reducer(class_ref(SlowGateReducer))
               .add_input_path("/wp-in")
               .set_output_path("/wp-out")
               .set_num_reduces(1)
               .set("test.reduce.gate", gate))
        job.submit()

        # wait until the maps are done (the job is mid-flight: reduce
        # gated) so the restart happens with live AM + reduce containers
        hist = f"/tmp/staging/{job.job_id}/history"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            done = [e for e in history.read_events(fs, hist)
                    if e["type"] == history.TASK_FINISHED]
            if len(done) >= 2:
                break
            time.sleep(0.2)
        assert len(done) >= 2, "maps never finished"

        cluster.yarn.restart_rm()
        time.sleep(1.0)
        os.remove(gate)

        assert job.wait_for_completion(timeout=90), job.diagnostics
        # work-preserving: the AM was NOT restarted — the RM knows only
        # attempt 1 and the history has each map exactly once
        evs = list(history.read_events(
            fs, f"/mr-history/done/{job.job_id}"))
        maps = [e["task_id"] for e in evs
                if e["type"] == history.TASK_FINISHED
                and e["task_type"] == "map"]
        assert len(maps) == len(set(maps)) == 2
        report = cluster.yarn.rm.apps[job._app_id].report()
        assert report.attempt_no == 1, "AM must not have been relaunched"


def test_failed_attempt_releases_its_containers(tmp_path):
    """A retried app must not leak the dead attempt's scheduler state:
    the failed attempt's containers are freed and queued for NM cleanup
    before the new attempt starts, and a duplicate failure report for
    the same dead attempt is dropped (review findings — leaked capacity
    per AM failure; double-spawned attempts on racing reports)."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.yarn.records import (ApplicationSubmissionContext,
                                         ContainerLaunchContext, NodeId,
                                         Resource, ResourceRequest)
    from hadoop_tpu.yarn.rm import ResourceManager, ResourceTrackerProtocol

    conf = Configuration(load_defaults=False)
    rm = ResourceManager(conf, state_dir=str(tmp_path / "state"))
    rm.init(conf)
    rm.start()
    tracker = ResourceTrackerProtocol(rm)
    try:
        nid = NodeId("h1", 9000)
        tracker.register_node_manager(
            nid.to_wire(), Resource(8192, 8).to_wire(), "h1:9000")
        app_id = rm.new_app_id()
        ctx = ApplicationSubmissionContext(
            app_id, "leaktest", ContainerLaunchContext(["true"], {}),
            Resource(512, 1), max_attempts=3, unmanaged=True)
        rm.submit_application(ctx, "u")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            att1 = rm.apps[app_id].current_attempt
            if att1 is not None and att1.attempt_id in rm.scheduler.apps:
                break
            time.sleep(0.05)
        first_id = att1.attempt_id
        # give the attempt a task container
        rm.scheduler.allocate(first_id, [ResourceRequest(
            10, 1, Resource(1024, 1))], [])
        tracker.node_heartbeat(nid.to_wire(), [])
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if rm.scheduler.apps[first_id].live_containers:
                break
            time.sleep(0.05)
        held = list(rm.scheduler.apps[first_id].live_containers)
        assert held, "no container ever allocated"

        att1.fail("synthetic AM death")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            att2 = rm.apps[app_id].current_attempt
            if att2 is not None and att2.attempt_id != first_id:
                break
            time.sleep(0.05)
        assert rm.apps[app_id].current_attempt.attempt_id != first_id
        # dead attempt is GONE from the scheduler and its container is
        # queued for NM cleanup
        assert first_id not in rm.scheduler.apps
        with rm.nodes_lock:
            cleanup = list(rm.nodes[nid].containers_to_cleanup)
        assert held[0] in cleanup

        # duplicate failure report for the SAME dead attempt (liveness
        # monitor racing the heartbeat handler) is dropped: still on
        # attempt 2, budget not double-charged
        att1.state = "RUNNING"  # the second racer's stale view
        att1.fail("duplicate report")
        time.sleep(0.5)
        att_now = rm.apps[app_id].current_attempt.attempt_id
        assert att_now.endswith("_02"), att_now
    finally:
        rm.stop()


def test_rm_recovers_past_torn_state_file(tmp_path):
    """One corrupt state file (pre-atomic-write crash, bitrot) costs that
    app its recovery — never the whole RM restart (review finding)."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.yarn.rm import ResourceManager

    state = tmp_path / "state"
    state.mkdir()
    (state / "application_1_1.json").write_text('{"truncated": ')
    conf = Configuration(load_defaults=False)
    rm = ResourceManager(conf, state_dir=str(state))
    rm.init(conf)
    rm.start()   # must not raise
    try:
        assert rm.apps == {}
    finally:
        rm.stop()


def test_nm_restart_completes_lost_containers(tmp_path):
    """An NM that re-registers WITHOUT its previous containers (it
    crashed; they died with it) must surface those containers as
    completed: scheduler usage deflates and the AM hears about the loss
    (review finding — they stayed 'live' forever)."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.yarn.records import (ApplicationSubmissionContext,
                                         ContainerLaunchContext, NodeId,
                                         Resource, ResourceRequest)
    from hadoop_tpu.yarn.rm import ResourceManager, ResourceTrackerProtocol

    conf = Configuration(load_defaults=False)
    rm = ResourceManager(conf, state_dir=str(tmp_path / "state"))
    rm.init(conf)
    rm.start()
    tracker = ResourceTrackerProtocol(rm)
    try:
        nid = NodeId("h1", 9000)
        tracker.register_node_manager(
            nid.to_wire(), Resource(8192, 8).to_wire(), "h1:9000")
        app_id = rm.new_app_id()
        ctx = ApplicationSubmissionContext(
            app_id, "nmloss", ContainerLaunchContext(["true"], {}),
            Resource(512, 1), unmanaged=True)
        rm.submit_application(ctx, "u")
        deadline = time.monotonic() + 10
        attempt_id = None
        while time.monotonic() < deadline:
            app = rm.apps[app_id]
            if app.current_attempt is not None and \
                    app.current_attempt.attempt_id in rm.scheduler.apps:
                attempt_id = app.current_attempt.attempt_id
                break
            time.sleep(0.05)
        rm.scheduler.allocate(attempt_id, [ResourceRequest(
            10, 1, Resource(1024, 1))], [])
        tracker.node_heartbeat(nid.to_wire(), [])
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if rm.scheduler.apps[attempt_id].live_containers:
                break
            time.sleep(0.05)
        assert rm.scheduler.apps[attempt_id].live_containers
        # NM restarts: re-registers with NO running containers
        tracker.register_node_manager(
            nid.to_wire(), Resource(8192, 8).to_wire(), "h1:9000",
            running_containers=[])
        assert not rm.scheduler.apps[attempt_id].live_containers
        assert rm.scheduler.apps[attempt_id].used.memory_mb == 0
        # the AM fetches the completion on its next allocate
        done, _ = rm.scheduler.allocate(attempt_id, [], [])
        statuses = rm.scheduler.apps[attempt_id].completed_unfetched
        assert statuses or done is not None
    finally:
        rm.stop()
