"""Long-running services framework on a live miniyarn cluster.

Mirrors the reference's service tests (ref: hadoop-yarn-services-core
TestYarnNativeServices.java — create service, wait STABLE, flex up,
component restart on exit, stop).
"""

import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniYARNCluster
from hadoop_tpu.yarn.services import (RESTART_NEVER, RESTART_ON_FAILURE,
                                      Component,
                                      ServiceClient, ServiceSpec)


@pytest.fixture(scope="module")
def cluster():
    with MiniYARNCluster(num_nodes=2) as c:
        yield c


def _wait(fn, timeout=30.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    raise AssertionError("condition not reached")


def test_service_lifecycle_flex_and_restart(cluster):
    spec = ServiceSpec("webapp", [
        Component("sleeper", 2, ["bash", "-c", "sleep 300"]),
        Component("flaky", 1, ["bash", "-c", "sleep 0.5; exit 1"],
                  restart_policy=RESTART_ON_FAILURE),
    ])
    sc = ServiceClient(cluster.rm_addr, Configuration(other=cluster.conf))
    try:
        app_id = sc.submit(spec)

        # Reaches target counts.
        st = _wait(lambda: (lambda s:
                            s if s["components"]["sleeper"]["running"] == 2
                            else None)(sc.status(app_id)))
        assert st["name"] == "webapp"

        # The flaky component keeps getting relaunched.
        st = _wait(lambda: (lambda s: s if s["restarts"] >= 2 else None)(
            sc.status(app_id)))
        assert st["restarts"] >= 2

        # Flex the sleeper up; a third instance appears.
        assert sc.flex(app_id, "sleeper", 3)
        _wait(lambda: sc.status(app_id)
              ["components"]["sleeper"]["running"] == 3)

        # Flex down; instances drop back (stopped via relaunch policy —
        # target enforcement happens on completion/reconcile).
        assert sc.flex(app_id, "sleeper", 1)

        # Stop: service unregisters cleanly and the app finishes.
        assert sc.stop(app_id, timeout=40.0)
    finally:
        sc.close()


def test_flex_unknown_component_rejected(cluster):
    spec = ServiceSpec("tiny-svc", [
        Component("only", 1, ["bash", "-c", "sleep 300"])])
    sc = ServiceClient(cluster.rm_addr, Configuration(other=cluster.conf))
    try:
        app_id = sc.submit(spec)
        _wait(lambda: sc.status(app_id)["components"]["only"]["running"]
              == 1)
        assert not sc.flex(app_id, "nope", 2)
        assert not sc.flex(app_id, "only", -1)
        assert sc.stop(app_id, timeout=40.0)
    finally:
        sc.close()


def test_restart_never_runs_once(cluster):
    """RESTART_NEVER (and ON_FAILURE with exit 0) components must run to
    completion exactly once, not be relaunched forever (ref:
    ComponentInstance terminated-instance handling)."""
    spec = ServiceSpec("oneshot", [
        Component("task", 1, ["bash", "-c", "exit 0"],
                  restart_policy=RESTART_NEVER),
        Component("sleeper", 1, ["bash", "-c", "sleep 300"]),
    ])
    sc = ServiceClient(cluster.rm_addr, Configuration(other=cluster.conf))
    try:
        app_id = sc.submit(spec)
        # The one-shot component finishes; its target shrinks to 0 so the
        # reconcile loop stops replacing it.
        _wait(lambda: (lambda s:
                       s["components"]["task"]["running"] == 0
                       and s["components"]["task"]["target"] == 0
                       and s["components"]["sleeper"]["running"] == 1)(
            sc.status(app_id)), timeout=40.0)
        # Give the loop time to (wrongly) relaunch, then re-check.
        time.sleep(2.0)
        st = sc.status(app_id)
        assert st["components"]["task"]["running"] == 0
        assert st["restarts"] == 0
        assert sc.stop(app_id, timeout=40.0)
    finally:
        sc.close()


def test_unmanaged_am_launcher(tmp_path):
    """The unmanaged-AM workflow (ref: hadoop-yarn-applications-
    unmanaged-am-launcher): the RM allocates no AM container; the AM
    runs as a LOCAL subprocess of the launcher, registers with the
    attempt id from the app report, gets real containers on the
    cluster, and completes the app."""
    import sys

    from hadoop_tpu.testing.minicluster import MiniYARNCluster
    from hadoop_tpu.yarn.client import YarnClient
    from hadoop_tpu.yarn.records import AppState
    from hadoop_tpu.yarn.unmanaged import launch

    with MiniYARNCluster(num_nodes=1,
                         base_dir=str(tmp_path / "c")) as cluster:
        # reuse the distributed-shell AM as the unmanaged master: it
        # reads HTPU_ATTEMPT_ID/HTPU_RM_ADDRESS from env, asks for n
        # containers, runs the command in them, unregisters
        am_cmd = [sys.executable, "-m",
                  "hadoop_tpu.examples.distributed_shell", "--am"]
        repo_root = str((tmp_path / "..").resolve())
        import hadoop_tpu
        import os as _os
        py_root = _os.path.dirname(_os.path.dirname(hadoop_tpu.__file__))
        app_id, rc = launch(
            cluster.rm_addr, am_cmd, name="unmanaged-dshell",
            env={"HTPU_DSHELL_N": "2",
                 "HTPU_DSHELL_CMD": "bash\x1f-c\x1ftrue",
                 "HTPU_DSHELL_MEM": "64",
                 "PYTHONPATH": py_root})
        assert rc == 0
        yc = YarnClient(cluster.rm_addr, cluster.conf)
        try:
            report = yc.wait_for_completion(app_id, timeout=30)
            assert report.state == AppState.FINISHED, report.diagnostics
        finally:
            yc.close()


def test_csi_volume_published_into_container(tmp_path):
    """CSI adaptor (ref: hadoop-yarn-csi): a container requesting an
    htpufs volume sees the DFS mounted under its workdir — the process
    reads a DFS file through PLAIN file IO — and the mount is gone
    after the container exits (before workdir cleanup)."""
    import os as _os

    import pytest as _pytest

    from hadoop_tpu.testing.minicluster import (MiniDFSCluster,
                                                MiniYARNCluster, fast_conf)
    from hadoop_tpu.yarn.client import YarnClient
    from hadoop_tpu.yarn.csi import DfsFuseDriver
    from hadoop_tpu.yarn.records import (ApplicationSubmissionContext,
                                         AppState, ContainerLaunchContext,
                                         Resource)

    if not DfsFuseDriver().available():
        _pytest.skip("fuse-dfs unavailable")

    dconf = fast_conf()
    dconf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=dconf,
                        base_dir=str(tmp_path / "dfs")) as dfs:
        dfs.wait_active()
        fs = dfs.get_filesystem()
        fs.mkdirs("/csi")
        fs.write_all("/csi/payload.txt", b"via-csi-volume\n")
        vol_id = f"htpufs://127.0.0.1:{dfs.namenode.http.port}"

        with MiniYARNCluster(num_nodes=1,
                             base_dir=str(tmp_path / "yarn")) as yarn:
            # in-process AM shortcut isn't needed: run a bare container
            # app via the unmanaged path? Simpler: use distributed
            # shell-style direct NM container — submit an app whose AM
            # command itself is the consumer, unmanaged, with volumes
            # not applicable... so drive the NM directly instead:
            nm = yarn.node_agents[0]
            from hadoop_tpu.yarn.records import Container, ContainerId, \
                NodeId
            from hadoop_tpu.ipc import get_proxy
            app_id, _ = YarnClient(yarn.rm_addr, yarn.conf)\
                .create_application()
            cid = ContainerId(app_id, 1, 1)
            marker = str(tmp_path / "out.txt")
            ctx = ContainerLaunchContext(
                ["bash", "-c",
                 f"cat data/csi/payload.txt > {marker}"],
                volumes=[{"driver": "htpufs", "id": vol_id,
                          "target": "data"}])
            port = nm.rpc.port
            c = Container(cid, nm.node_id, Resource(64, 1),
                          nm_address=f"127.0.0.1:{port}")
            proxy = get_proxy("ContainerManagerProtocol",
                              ("127.0.0.1", port))
            proxy.start_container(c.to_wire(), ctx.to_wire())
            import time as _time
            deadline = _time.monotonic() + 30
            while _time.monotonic() < deadline:
                st = proxy.get_container_status(cid.to_wire())
                if st and st.get("st") == "COMPLETE":
                    break
                _time.sleep(0.2)
            assert _os.path.exists(marker), "container never wrote output"
            assert open(marker, "rb").read() == b"via-csi-volume\n"
            # the fuse mount is gone from the workdir
            workdir = _os.path.join(nm.work_root, str(cid))
            assert not _os.path.ismount(_os.path.join(workdir, "data"))
