"""YARN component unit tests: dispatcher, state machines, schedulers.
(Parity targets: ref TestAsyncDispatcher, TestStateMachine (implicit via
rmapp tests), TestCapacityScheduler, TestFifoScheduler.)"""

import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.common import (AsyncDispatcher, Event,
                                    InvalidStateTransitionError,
                                    StateMachineFactory)
from hadoop_tpu.yarn.records import (ApplicationId, ContainerId, NodeId,
                                     Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import CapacityScheduler, FifoScheduler


# ----------------------------------------------------------------- records


def test_resource_arithmetic():
    a = Resource(1024, 2, 1)
    b = Resource(512, 1, 0)
    assert b.fits_in(a)
    assert not a.fits_in(b)
    assert a.add(b).memory_mb == 1536
    assert a.subtract(b).tpu_chips == 1
    total = Resource(10240, 20, 8)
    assert Resource(1024, 1, 4).dominant_share(total) == 0.5  # tpu dominates


def test_id_formats():
    app = ApplicationId(1700000000, 7)
    assert str(app) == "application_1700000000_0007"
    assert ApplicationId.parse(str(app)) == app
    cid = ContainerId(app, 1, 42)
    assert str(cid) == "container_1700000000_0007_01_000042"
    assert ContainerId.from_wire(cid.to_wire()) == cid


# -------------------------------------------------------------- dispatcher


def test_dispatcher_routes_and_survives_handler_errors():
    d = AsyncDispatcher()
    seen = []

    def handler(ev):
        if ev.etype == "boom":
            raise RuntimeError("handler failure")
        seen.append(ev.etype)

    d.register("cat", handler)
    d.init(Configuration(load_defaults=False))
    d.start()
    try:
        d.dispatch("cat", Event("a"))
        d.dispatch("cat", Event("boom"))  # must not kill the loop
        d.dispatch("cat", Event("b"))
        deadline = time.monotonic() + 5
        while len(seen) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert seen == ["a", "b"]
    finally:
        d.stop()


# ------------------------------------------------------------ state machine


def test_state_machine_transitions():
    hooks = []
    factory = (StateMachineFactory("NEW")
               .add("NEW", "RUNNING", "start",
                    lambda o, p: hooks.append(("start", p)))
               .add("RUNNING", ("DONE", "FAILED"), "finish",
                    lambda o, p: "DONE" if p == 0 else "FAILED"))
    sm = factory.make(object())
    assert sm.state == "NEW"
    sm.handle("start", "payload")
    assert sm.state == "RUNNING"
    assert hooks == [("start", "payload")]
    with pytest.raises(InvalidStateTransitionError):
        sm.handle("start")
    sm.handle("finish", 1)
    assert sm.state == "FAILED"

    sm2 = factory.make(object())
    sm2.handle("start", None)
    sm2.handle("finish", 0)
    assert sm2.state == "DONE"


# --------------------------------------------------------------- scheduler


def _mk_cid(attempt_id, seq):
    parts = attempt_id.rsplit("_", 1)
    return ContainerId(ApplicationId.parse(parts[0]), int(parts[1]), seq)


def _fifo():
    return FifoScheduler(Configuration(load_defaults=False), _mk_cid)


def test_fifo_allocates_on_heartbeat():
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 2, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 2
    assert all(c.node_id == n1 for c in allocated)
    assert s.nodes[n1].available.memory_mb == 4096 - 2048


def test_fifo_respects_capacity_limits():
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(2048, 8, 0), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 5, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 2  # only 2 fit
    # Free one → next heartbeat grants one more.
    s.allocate("application_1_0001_01", [],
               [allocated[0].container_id])
    s.node_heartbeat(n1)
    more, _ = s.allocate("application_1_0001_01", [], [])
    assert len(more) == 1


def test_tpu_chips_are_scheduling_dimension():
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(8192, 16, 4), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 8, Resource(512, 1, 1))], [])
    s.node_heartbeat(n1)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 4  # chip-bound, not memory-bound
    assert s.nodes[n1].available.tpu_chips == 0


def test_node_locality_request():
    s = _fifo()
    s.add_node(NodeId("h1", 1), Resource(4096, 8, 0), "h1:1")
    s.add_node(NodeId("h2", 2), Resource(4096, 8, 0), "h2:2")
    s.add_app("application_1_0001_01", "default", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 1, Resource(512, 1), host="h2")], [])
    s.node_heartbeat(NodeId("h1", 1))  # wrong host: nothing
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert allocated == []
    s.node_heartbeat(NodeId("h2", 2))
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 1
    assert allocated[0].node_id.host == "h2"


def test_node_removal_reports_lost_containers():
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 1, Resource(512, 1))], [])
    s.node_heartbeat(n1)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 1
    s.remove_node(n1)
    _, completed = s.allocate("application_1_0001_01", [], [])
    assert len(completed) == 1
    assert completed[0].exit_code == -100  # lost


def _capacity(queues="a,b", caps=None):
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", queues)
    for q, c in (caps or {}).items():
        conf.set(f"yarn.scheduler.capacity.root.{q}.capacity", c)
    return CapacityScheduler(conf, _mk_cid)


def test_capacity_unknown_queue_rejected():
    s = _capacity()
    with pytest.raises(ValueError, match="unknown queue"):
        s.add_app("application_1_0001_01", "nope", "u")


def test_capacity_under_served_queue_wins():
    s = _capacity(caps={"a": "50", "b": "50"})
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.add_app("application_1_0001_01", "a", "u")
    s.add_app("application_1_0002_01", "b", "u")
    # Queue a grabs 3GB of 4GB (75% > its 50% share).
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 3, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    a1, _ = s.allocate("application_1_0001_01", [], [])
    assert len(a1) == 3
    # Now both queues ask for the last GB; b (0% used of 50%) must win.
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 1, Resource(1024, 1))], [])
    s.allocate("application_1_0002_01",
               [ResourceRequest(1, 1, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    a2, _ = s.allocate("application_1_0002_01", [], [])
    assert len(a2) == 1
    a1b, _ = s.allocate("application_1_0001_01", [], [])
    assert a1b == []


def test_capacity_max_capacity_hard_cap():
    s = _capacity(caps={"a": "50", "b": "50"})
    conf_cap = s.queues["a"]
    conf_cap.max_capacity = 0.5  # a may never exceed half the cluster
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.add_app("application_1_0001_01", "a", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 4, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    a1, _ = s.allocate("application_1_0001_01", [], [])
    assert len(a1) == 2  # capped at 50% despite free space
    s.node_heartbeat(n1)
    a2, _ = s.allocate("application_1_0001_01", [], [])
    assert a2 == []


# ------------------------------------------------------------- node labels

def test_node_label_partitions_are_exclusive():
    """A labeled request only lands on matching nodes; unlabeled
    requests never land on labeled nodes (ref: exclusive node-label
    partitions)."""
    conf = Configuration(load_defaults=False)
    conf.set("yarn.node-labels.map", "g1=gpu")
    conf.set("yarn.scheduler.capacity.root.queues", "a")
    conf.set("yarn.scheduler.capacity.root.a.accessible-node-labels", "gpu")
    s = CapacityScheduler(conf, _mk_cid)
    gpu_node = NodeId("g1", 1)
    cpu_node = NodeId("c1", 1)
    s.add_node(gpu_node, Resource(8192, 8, 4), "g1:1")
    s.add_node(cpu_node, Resource(8192, 8, 0), "c1:1")
    s.add_app("application_1_0001_01", "a", "u")
    s.allocate("application_1_0001_01", [
        ResourceRequest(1, 1, Resource(1024, 1), node_label="gpu"),
        ResourceRequest(2, 1, Resource(1024, 1)),
    ], [])
    s.node_heartbeat(cpu_node)
    s.node_heartbeat(gpu_node)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert len(allocated) == 2
    by_prio = {c.node_id.host for c in allocated}
    # the labeled ask landed on g1, the unlabeled one on c1
    placed = sorted((c.node_id.host) for c in allocated)
    assert placed == ["c1", "g1"]


def test_node_label_queue_acl_enforced():
    """A queue without access to a label never allocates there (ref:
    accessible-node-labels ACL)."""
    conf = Configuration(load_defaults=False)
    conf.set("yarn.node-labels.map", "g1=gpu")
    conf.set("yarn.scheduler.capacity.root.queues", "a")
    # queue a has NO accessible-node-labels
    s = CapacityScheduler(conf, _mk_cid)
    gpu_node = NodeId("g1", 1)
    s.add_node(gpu_node, Resource(8192, 8, 4), "g1:1")
    s.add_app("application_1_0001_01", "a", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 1, Resource(1024, 1),
                                node_label="gpu")], [])
    s.node_heartbeat(gpu_node)
    allocated, _ = s.allocate("application_1_0001_01", [], [])
    assert allocated == []


# ------------------------------------------------------------ reservations

def _reserved_capacity(now):
    from hadoop_tpu.yarn.scheduler import Reservation
    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", "a,b")
    conf.set("yarn.scheduler.capacity.root.a.capacity", "50")
    conf.set("yarn.scheduler.capacity.root.b.capacity", "50")
    s = CapacityScheduler(conf, _mk_cid, now_fn=lambda: now[0])
    return s, Reservation


def test_reservation_honored_at_allocation():
    """During its window, a reservation's envelope is held: ordinary
    apps cannot consume it, the reserved app gets it even past its
    queue share (ref: ReservationSystem + PlanFollower)."""
    now = [100.0]
    s, Reservation = _reserved_capacity(now)
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.submit_reservation(Reservation(
        "res-1", "a", Resource(1024, 1), 2, start=50.0, deadline=200.0))

    # An ordinary app asks for everything — it must be stopped short of
    # the reserved 2048 MB.
    s.add_app("application_1_0001_01", "b", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 4, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    got, _ = s.allocate("application_1_0001_01", [], [])
    assert len(got) == 2, f"ordinary app got {len(got)}, reserve violated"

    # The reservation's app claims its envelope.
    s.add_app("application_1_0002_01", "res-1", "u2")
    s.allocate("application_1_0002_01",
               [ResourceRequest(1, 2, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    got2, _ = s.allocate("application_1_0002_01", [], [])
    assert len(got2) == 2, "reserved app denied its envelope"


def test_reservation_expires_and_frees_headroom():
    now = [100.0]
    s, Reservation = _reserved_capacity(now)
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(4096, 8, 0), "h1:1")
    s.submit_reservation(Reservation(
        "res-1", "a", Resource(1024, 1), 2, start=50.0, deadline=200.0))
    s.add_app("application_1_0001_01", "b", "u")
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 4, Resource(1024, 1))], [])
    s.node_heartbeat(n1)
    got, _ = s.allocate("application_1_0001_01", [], [])
    assert len(got) == 2
    now[0] = 250.0  # window passed
    s.node_heartbeat(n1)
    got, _ = s.allocate("application_1_0001_01", [], [])
    assert len(got) == 2  # the held-back headroom is released


def test_reservation_admission_rejects_overcommit():
    now = [0.0]
    s, Reservation = _reserved_capacity(now)
    s.add_node(NodeId("h1", 1), Resource(4096, 8, 0), "h1:1")
    s.submit_reservation(Reservation(
        "res-1", "a", Resource(2048, 2), 1, start=0.0, deadline=100.0))
    with pytest.raises(ValueError, match="rejected"):
        s.submit_reservation(Reservation(
            "res-2", "b", Resource(4096, 4), 1, start=50.0,
            deadline=150.0))
    # non-overlapping window is fine
    s.submit_reservation(Reservation(
        "res-3", "b", Resource(4096, 4), 1, start=100.0, deadline=150.0))
    assert s.delete_reservation("res-1")
    assert not s.delete_reservation("res-1")


# --------------------------------------------------- opportunistic containers

def test_opportunistic_allocation_past_capacity():
    """OPPORTUNISTIC asks allocate immediately even on a FULL cluster
    (queued best-effort), while GUARANTEED asks wait for capacity
    (ref: YARN-2882 OpportunisticContainerAllocatorAMService)."""
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(1024, 2, 0), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    # fill the node with a guaranteed container
    s.allocate("application_1_0001_01",
               [ResourceRequest(1, 1, Resource(1024, 2))], [])
    s.node_heartbeat(n1)
    got, _ = s.allocate("application_1_0001_01", [], [])
    assert len(got) == 1

    # guaranteed ask: blocked (node full)
    s.allocate("application_1_0001_01",
               [ResourceRequest(2, 1, Resource(512, 1))], [])
    s.node_heartbeat(n1)
    got, _ = s.allocate("application_1_0001_01", [], [])
    assert got == []

    # opportunistic ask: allocated instantly, past capacity
    got, _ = s.allocate("application_1_0001_01", [
        ResourceRequest(3, 2, Resource(512, 1),
                        execution_type=ResourceRequest
                        .EXEC_OPPORTUNISTIC)], [])
    assert len(got) == 2
    assert all(c.node_id == n1 for c in got)
    # releasing O-containers does not free (never held) node capacity
    avail_before = s.nodes[n1].available.memory_mb
    s.allocate("application_1_0001_01", [],
               [c.container_id for c in got])
    assert s.nodes[n1].available.memory_mb == avail_before
    assert not s.nodes[n1].opportunistic


def test_opportunistic_queue_cap_per_node():
    s = _fifo()
    n1 = NodeId("h1", 1)
    s.add_node(n1, Resource(1024, 2, 0), "h1:1")
    s.add_app("application_1_0001_01", "default", "u")
    got, _ = s.allocate("application_1_0001_01", [
        ResourceRequest(1, 50, Resource(128, 1),
                        execution_type=ResourceRequest
                        .EXEC_OPPORTUNISTIC)], [])
    assert len(got) == s.MAX_OPPORTUNISTIC_PER_NODE  # bounded queue
    # the remainder stays pending and drains as queue slots free
    s.allocate("application_1_0001_01", [],
               [c.container_id for c in got[:4]])
    s.node_heartbeat(n1)
    more, _ = s.allocate("application_1_0001_01", [], [])
    assert len(more) == 4  # refilled up to the cap
